"""Shared aiohttp client-session management and capped body reads."""

from __future__ import annotations

import asyncio

import aiohttp


async def read_body_limited(request, limit: int) -> bytes | None:
    """Request body within ``limit`` bytes, else None (callers answer 413).
    0 = unlimited. Checks the declared length first (cheap refusal), then
    reads the stream INCREMENTALLY and aborts the moment the running total
    exceeds the cap — a chunked body with no declared length must never
    buffer more than limit+chunk bytes. Shared by the gateway's edge caps
    and the task-store surface (both ride apps whose aiohttp cap is
    disabled)."""
    if not limit:
        return await request.read()
    if (request.content_length or 0) > limit:
        return None
    chunks: list[bytes] = []
    total = 0
    while True:
        chunk = await request.content.readany()
        if not chunk:
            return b"".join(chunks)
        total += len(chunk)
        if total > limit:
            return None
        chunks.append(chunk)


class SessionHolder:
    """Lazily-created, recreate-if-closed ClientSession with a creation guard
    so concurrent first calls can't leak an extra session."""

    def __init__(self, session: aiohttp.ClientSession | None = None,
                 timeout: float | None = None,
                 headers: dict[str, str] | None = None,
                 limit: int | None = None):
        """``limit``: max concurrent connections for the lazily-created
        session (0 = unbounded). None keeps aiohttp's default of 100 —
        components whose in-flight request count is bounded elsewhere (the
        dispatcher's worker loops, the gateway's inbound connections) pass 0
        so a 100-connection pool doesn't silently cap a concurrency knob
        set higher."""
        self._session = session
        self._timeout = timeout
        self._headers = headers
        self._limit = limit
        self._create_lock: asyncio.Lock | None = None

    async def get(self) -> aiohttp.ClientSession:
        if self._session is not None and not self._session.closed:
            return self._session
        if self._create_lock is None:
            self._create_lock = asyncio.Lock()
        async with self._create_lock:
            if self._session is None or self._session.closed:
                kw = {}
                if self._timeout is not None:
                    kw["timeout"] = aiohttp.ClientTimeout(total=self._timeout)
                if self._headers:
                    kw["headers"] = dict(self._headers)
                if self._limit is not None:
                    kw["connector"] = aiohttp.TCPConnector(limit=self._limit)
                self._session = aiohttp.ClientSession(**kw)
        return self._session

    async def close(self) -> None:
        if self._session is not None and not self._session.closed:
            await self._session.close()
