"""Shared aiohttp client-session management."""

from __future__ import annotations

import asyncio

import aiohttp


class SessionHolder:
    """Lazily-created, recreate-if-closed ClientSession with a creation guard
    so concurrent first calls can't leak an extra session."""

    def __init__(self, session: aiohttp.ClientSession | None = None,
                 timeout: float | None = None,
                 headers: dict[str, str] | None = None,
                 limit: int | None = None):
        """``limit``: max concurrent connections for the lazily-created
        session (0 = unbounded). None keeps aiohttp's default of 100 —
        components whose in-flight request count is bounded elsewhere (the
        dispatcher's worker loops, the gateway's inbound connections) pass 0
        so a 100-connection pool doesn't silently cap a concurrency knob
        set higher."""
        self._session = session
        self._timeout = timeout
        self._headers = headers
        self._limit = limit
        self._create_lock: asyncio.Lock | None = None

    async def get(self) -> aiohttp.ClientSession:
        if self._session is not None and not self._session.closed:
            return self._session
        if self._create_lock is None:
            self._create_lock = asyncio.Lock()
        async with self._create_lock:
            if self._session is None or self._session.closed:
                kw = {}
                if self._timeout is not None:
                    kw["timeout"] = aiohttp.ClientTimeout(total=self._timeout)
                if self._headers:
                    kw["headers"] = dict(self._headers)
                if self._limit is not None:
                    kw["connector"] = aiohttp.TCPConnector(limit=self._limit)
                self._session = aiohttp.ClientSession(**kw)
        return self._session

    async def close(self) -> None:
        if self._session is not None and not self._session.closed:
            await self._session.close()
