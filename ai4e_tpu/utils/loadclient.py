"""Shared closed-loop load-measurement client.

Used by ``bench.py`` (in-proc platform) and ``examples/loadgen.py`` (any
live deployment): N clients each keep exactly one request in flight against
an async task route (POST → long-poll ``/task/{id}``) or a sync route
(POST → response), with an untimed steady-state ramp before the measured
window opens.

Error tolerance is the point of sharing this: a non-503 error response, an
undecodable body, a vanished task (404 after the reaper), or a transport
error counts as one failed request and the run continues — a load tool
pointed at a production topology must survive exactly the conditions it
creates.
"""

from __future__ import annotations

import asyncio
import json
import time


def _latency_percentiles(window_lat: list[float]) -> dict:
    """p50/p95/p99 (ms) over a sorted window-latency list — ONE convention
    shared by the closed and open loops so their reported numbers stay
    comparable."""
    def pctl(q: float) -> float:
        return round(
            window_lat[max(0, int(len(window_lat) * q) - 1)] * 1000, 1)
    return {
        "p50_latency_ms": round(window_lat[len(window_lat) // 2] * 1000, 1),
        "p95_latency_ms": pctl(0.95),
        "p99_latency_ms": pctl(0.99),
    }


def _window_error_delta(close: dict, mark: dict) -> dict:
    """Per-kind client-error counts inside the measured window (close
    snapshot minus mark snapshot, zero-delta kinds dropped)."""
    return {k: close["errors"].get(k, 0) - mark["errors"].get(k, 0)
            for k in close["errors"]
            if close["errors"].get(k, 0) - mark["errors"].get(k, 0) > 0}


def _backoff(resp) -> float:
    """Sleep for a backpressure response: Retry-After when the server sent
    one (capped at 2 s — a closed-loop client that idles longer just
    under-measures), else a short yield."""
    retry_after = resp.headers.get("Retry-After")
    try:
        return min(float(retry_after), 2.0) if retry_after else 0.05
    except ValueError:
        return 0.05


async def run_closed_loop(
    session,
    *,
    post_url: str,
    payload: bytes,
    headers: dict,
    mode: str = "async",
    status_url_for=None,
    concurrency: int = 64,
    duration: float = 20.0,
    ramp: float = 5.0,
    task_timeout: float = 120.0,
    poll_wait: float = 30.0,
    post_url_for=None,
    headers_for=None,
    deadline_s: float | None = None,
    events_url_for=None,
    tenant_names: dict | None = None,
) -> dict:
    """Drive ``post_url`` closed-loop; returns window stats.

    ``status_url_for(task_id) -> url`` is required in async mode.
    ``post_url_for() -> url`` (optional) picks the POST target per request —
    the bench's duplicate-request mix rides this (identical requests POST
    the bare route, unique ones carry a never-repeating query param).
    ``headers_for() -> dict`` (optional) adds per-request headers on top of
    ``headers`` — the bench's deadline/priority mix rides this
    (admission control).
    ``deadline_s`` (optional): the per-request latency budget the traffic
    carries; completions are additionally bucketed into goodput (finished
    within the budget) vs ``late``, and tasks the platform shed on their
    deadline (terminal ``expired`` status / 504) count as ``expired``,
    not failed.
    ``tenant_names`` (optional): subscription key → tenant name. When
    set, every outcome is additionally bucketed by the tenant whose key
    the request carried (``Ocp-Apim-Subscription-Key``, set via
    ``headers``/``headers_for``) and the window JSON gains a
    ``by_tenant`` block — completions, goodput, and the tenant-quota
    429s (``quota_shed``) the gateway's per-tenant bucket refused
    (docs/tenancy.md). Keys absent from the map bucket under ``""``.
    ``events_url_for(task_id) -> url`` (optional, async mode): follow the
    task's SSE event stream (``GET /task/{id}/events``, pipeline
    platforms — docs/pipelines.md) instead of long-polling, recording
    **time-to-first-partial** — POST to the first stage partial (a
    ``stage`` event reaching completed/cached, or any ``chunk``) — and
    scoring the terminal event; the window JSON then carries
    ``time_to_first_partial_ms_p50``/``_p95`` and ``first_partials``. A
    failed/closed stream falls back to the ordinary status poll.
    Returns ``{"value", "p50_latency_ms", "p95_latency_ms", "completed",
    "failed", "expired", "duration_s", ...}`` where value is
    completions/second inside the measurement window that opens after
    ``ramp`` seconds; with ``deadline_s`` set the dict gains
    ``goodput`` (within-deadline completions/second) and ``late``.
    """
    import aiohttp

    if mode == "async" and status_url_for is None:
        raise ValueError("async mode needs status_url_for")

    latencies: list[float] = []
    ttfps: list[float] = []  # time-to-first-partial samples (events mode)
    completed = 0
    failed = 0
    expired = 0
    good = 0  # completions within deadline_s (== completed when unset)
    # Loadgen honesty (ISSUE 11): every POST the client actually attempted
    # (backpressure re-entries included) and a client-side error taxonomy,
    # so the window JSON records OFFERED vs ACHIEVED rate — a CPU-bound
    # run cannot silently report a lower rate as if it were the target.
    offered = 0
    errors: dict[str, int] = {}

    def _err(kind: str) -> None:
        errors[kind] = errors.get(kind, 0) + 1
    # Per-priority-class accounting, keyed by the X-Priority header each
    # request carried ("" = unlabeled). Only populated when headers_for
    # labels traffic — the bench's --mix profiles report per-class
    # goodput and deadline-miss rate off these buckets.
    by_class: dict[str, dict] = {}

    def _bucket(cls: str) -> dict:
        b = by_class.get(cls)
        if b is None:
            b = by_class[cls] = {"completed": 0, "good": 0, "failed": 0,
                                 "expired": 0}
        return b
    # Per-tenant accounting (docs/tenancy.md), keyed by the tenant whose
    # subscription key each request carried — only populated when the
    # caller supplies the key → name map.
    by_tenant: dict[str, dict] = {}

    def _tbucket(name: str) -> dict:
        b = by_tenant.get(name)
        if b is None:
            b = by_tenant[name] = {"offered": 0, "completed": 0, "good": 0,
                                   "failed": 0, "expired": 0,
                                   "quota_shed": 0}
        return b

    def _tenant_of(hdrs: dict) -> str | None:
        if tenant_names is None:
            return None
        return tenant_names.get(
            hdrs.get("Ocp-Apim-Subscription-Key", ""), "")

    def _headers() -> dict:
        if headers_for is None:
            return headers
        return {**headers, **headers_for()}

    def _score_completion(elapsed: float, cls: str, tname=None) -> None:
        nonlocal completed, good
        latencies.append(elapsed)
        completed += 1
        _bucket(cls)["completed"] += 1
        in_deadline = deadline_s is None or elapsed <= deadline_s
        if in_deadline:
            good += 1
            _bucket(cls)["good"] += 1
        if tname is not None:
            _tbucket(tname)["completed"] += 1
            if in_deadline:
                _tbucket(tname)["good"] += 1

    def _score_failed(cls: str, tname=None) -> None:
        nonlocal failed
        failed += 1
        _bucket(cls)["failed"] += 1
        if tname is not None:
            _tbucket(tname)["failed"] += 1

    def _score_expired(cls: str, tname=None) -> None:
        nonlocal expired
        expired += 1
        _bucket(cls)["expired"] += 1
        if tname is not None:
            _tbucket(tname)["expired"] += 1

    def _score_backpressure(resp, tname=None) -> None:
        # A tenant-quota 429 is the tenant's OWN contract (shed, carries
        # Retry-After) — bucket it to the tenant so the noisy-neighbor
        # A/B can show who paid; other 429/503s are platform pressure.
        reason = resp.headers.get("X-Shed-Reason", "")
        if "tenant-quota" in reason:
            _err("tenant_quota_429")
            if tname is not None:
                _tbucket(tname)["quota_shed"] += 1
        else:
            _err(f"backpressure_{resp.status}")

    def _score_terminal(status: str, elapsed: float, cls: str,
                        tname=None) -> None:
        # "failed" FIRST — the platform's canonical bucketing
        # (TaskStatus.canonical) tests it first.
        if "failed" in status:
            _score_failed(cls, tname)
        elif "completed" in status:
            _score_completion(elapsed, cls, tname)
        elif "expired" in status:
            _score_expired(cls, tname)
        else:
            _score_failed(cls, tname)  # stream ended on a non-terminal status

    async def _follow_events(task_id: str, t0: float, cls: str,
                             deadline: float, tname=None) -> bool:
        """Consume the task's SSE stream: record the first partial, score
        the terminal event. True when the request was scored; False →
        the caller falls back to status polling."""
        saw_partial = False
        try:
            budget = max(1.0, deadline - time.perf_counter())
            async with session.get(
                    events_url_for(task_id),
                    params={"wait": str(round(budget, 1))},
                    headers=headers) as resp:
                if resp.status != 200:
                    return False
                current: dict = {}
                async for raw in resp.content:
                    if time.perf_counter() > deadline:
                        # stuck task: don't hang the run
                        _score_failed(cls, tname)
                        return True
                    line = raw.decode("utf-8").rstrip("\r\n")
                    if line.startswith(":"):
                        continue  # keep-alive
                    if line:
                        if line.startswith("event: "):
                            current["event"] = line[len("event: "):]
                        elif line.startswith("data: "):
                            try:
                                current["data"] = json.loads(
                                    line[len("data: "):])
                            except ValueError:
                                pass
                        continue
                    etype = current.get("event")
                    data = current.get("data") or {}
                    current = {}
                    if etype in ("stage", "chunk") and not saw_partial:
                        state = data.get("state", "")
                        if etype == "chunk" or state in ("completed",
                                                         "cached"):
                            saw_partial = True
                            ttfps.append(time.perf_counter() - t0)
                    elif etype == "terminal":
                        _score_terminal(data.get("Status", ""),
                                        time.perf_counter() - t0, cls,
                                        tname)
                        return True
        except (aiohttp.ClientError, asyncio.TimeoutError):
            return False
        return False  # stream closed without a terminal event

    async def one_async() -> None:
        nonlocal offered
        t0 = time.perf_counter()
        url = post_url if post_url_for is None else post_url_for()
        hdrs = _headers()
        cls = hdrs.get("X-Priority", "")
        tname = _tenant_of(hdrs)
        offered += 1
        if tname is not None:
            _tbucket(tname)["offered"] += 1
        try:
            async with session.post(url, data=payload,
                                    headers=hdrs) as resp:
                if resp.status in (503, 429):
                    # Backpressure (admission 503 / per-key throttle 429 /
                    # tenant quota 429): not a failure — yield briefly and
                    # re-enter. The client honors Retry-After when present,
                    # capped so one long hint can't idle the closed loop
                    # past the window.
                    _score_backpressure(resp, tname)
                    await asyncio.sleep(_backoff(resp))
                    return
                if resp.status == 504:  # shed: budget spent at the edge
                    _err("shed_504")
                    _score_expired(cls, tname)
                    return
                if resp.status >= 400:
                    _err(f"http_{resp.status}")
                    _score_failed(cls, tname)
                    return
                task = await resp.json()
            task_id = task["TaskId"]
        except asyncio.TimeoutError:
            _err("timeout")
            _score_failed(cls, tname)
            return
        except aiohttp.ClientError as exc:
            _err("connect_error"
                 if isinstance(exc, aiohttp.ClientConnectorError)
                 else "transport_error")
            _score_failed(cls, tname)
            return
        except (ValueError, KeyError, TypeError):
            _err("bad_response")
            _score_failed(cls, tname)
            return
        deadline = t0 + task_timeout
        if events_url_for is not None:
            if await _follow_events(task_id, t0, cls, deadline, tname):
                return
            # Stream unavailable/interrupted: poll like everyone else.
        while True:
            try:
                async with session.get(status_url_for(task_id),
                                       params={"wait": str(int(poll_wait))},
                                       headers=headers) as resp:
                    if resp.status == 404:  # reaped/evicted task
                        _err("task_poll_404")
                        _score_failed(cls, tname)
                        return
                    record = await resp.json()
                status = record["Status"]
            except (aiohttp.ClientError, asyncio.TimeoutError, ValueError,
                    KeyError, TypeError):
                _err("poll_transport")
                _score_failed(cls, tname)
                return
            # "failed" FIRST — the platform's canonical bucketing
            # (TaskStatus.canonical) tests it first, so a status carrying
            # both words counts the same here as in the store's sets.
            if "failed" in status:
                _score_failed(cls, tname)
                return
            if "completed" in status:
                _score_completion(time.perf_counter() - t0, cls, tname)
                return
            if "expired" in status:
                # Admission shed the task on its deadline (terminal) —
                # shed work, not a platform failure.
                _score_expired(cls, tname)
                return
            if time.perf_counter() > deadline:  # stuck task: don't hang the run
                _err("stuck_timeout")
                _score_failed(cls, tname)
                return

    async def one_sync() -> None:
        # 503 backpressure: sleep briefly and return (neither completed nor
        # failed) — client_loop re-enters until the run deadline, same as
        # one_async, so sustained backpressure can never outlive the run.
        nonlocal offered
        t0 = time.perf_counter()
        url = post_url if post_url_for is None else post_url_for()
        hdrs = _headers()
        cls = hdrs.get("X-Priority", "")
        tname = _tenant_of(hdrs)
        offered += 1
        if tname is not None:
            _tbucket(tname)["offered"] += 1
        try:
            async with session.post(url, data=payload,
                                    headers=hdrs) as resp:
                if resp.status in (503, 429):
                    _score_backpressure(resp, tname)
                    await asyncio.sleep(_backoff(resp))
                    return
                if resp.status == 504:  # admission shed on deadline
                    _err("shed_504")
                    _score_expired(cls, tname)
                    return
                await resp.read()
                ok = resp.status == 200
                if not ok:
                    _err(f"http_{resp.status}")
        except asyncio.TimeoutError:
            _err("timeout")
            ok = False
        except aiohttp.ClientError as exc:
            _err("connect_error"
                 if isinstance(exc, aiohttp.ClientConnectorError)
                 else "transport_error")
            ok = False
        if ok:
            _score_completion(time.perf_counter() - t0, cls, tname)
        else:
            _score_failed(cls, tname)

    one = one_sync if mode == "sync" else one_async

    async def client_loop(stop_at: float) -> None:
        while time.perf_counter() < stop_at:
            await one()

    # Ramp: run load untimed until the pipeline is in steady state (cold
    # start — empty queues, small batches, cache touches — would otherwise
    # land inside the measured window). In-flight work at the open and
    # close of the window cancels to first order.
    mark: dict = {}
    close: dict = {}

    def _class_snapshot() -> dict:
        return {cls: dict(b) for cls, b in by_class.items()}

    def _tenant_snapshot() -> dict:
        return {name: dict(b) for name, b in by_tenant.items()}

    async def open_window() -> None:
        await asyncio.sleep(ramp)
        mark.update(t=time.perf_counter(), completed=completed,
                    failed=failed, expired=expired, good=good,
                    offered=offered, errors=dict(errors),
                    n_lat=len(latencies), n_ttfp=len(ttfps),
                    by_class=_class_snapshot(),
                    by_tenant=_tenant_snapshot())

    async def close_window() -> None:
        # Snapshot AT stop_at, not after the drain: gather() returns only
        # once every in-flight request resolves, and a single stuck task
        # would stretch the denominator by up to task_timeout with no
        # completions — deflating throughput several-fold.
        await asyncio.sleep(ramp + duration)
        close.update(t=time.perf_counter(), completed=completed,
                     failed=failed, expired=expired, good=good,
                     offered=offered, errors=dict(errors),
                     n_lat=len(latencies), n_ttfp=len(ttfps),
                     by_class=_class_snapshot(),
                     by_tenant=_tenant_snapshot())

    stop_at = time.perf_counter() + ramp + duration
    await asyncio.gather(open_window(), close_window(),
                         *[client_loop(stop_at) for _ in range(concurrency)])
    elapsed = close["t"] - mark["t"]

    window_lat = sorted(latencies[mark["n_lat"]:close["n_lat"]]) or [0.0]
    n = close["completed"] - mark["completed"]

    n_offered = close["offered"] - mark["offered"]
    window_errors = _window_error_delta(close, mark)
    out = {
        "value": round(n / elapsed, 2),
        **_latency_percentiles(window_lat),
        "completed": n,
        "failed": close["failed"] - mark["failed"],
        "expired": close["expired"] - mark["expired"],
        "duration_s": round(elapsed, 1),
        # Honesty block (ISSUE 11): what the client actually ATTEMPTED vs
        # what completed, plus the client-side error taxonomy — a
        # CPU-bound run reports its shortfall instead of silently
        # presenting the achieved rate as the target.
        "offered": n_offered,
        "offered_rate": round(n_offered / elapsed, 2),
        "achieved_rate": round(n / elapsed, 2),
        "client_errors": window_errors,
    }
    if events_url_for is not None:
        # Time-to-first-partial (docs/pipelines.md): POST → first stage
        # partial on the event stream, window-sliced like the latencies.
        window_ttfp = sorted(ttfps[mark["n_ttfp"]:close["n_ttfp"]])
        out["first_partials"] = len(window_ttfp)
        if window_ttfp:
            def tp(q: float) -> float:
                idx = max(0, int(len(window_ttfp) * q) - 1)
                return round(window_ttfp[idx] * 1000, 1)
            out["time_to_first_partial_ms_p50"] = round(
                window_ttfp[len(window_ttfp) // 2] * 1000, 1)
            out["time_to_first_partial_ms_p95"] = tp(0.95)
    if deadline_s is not None:
        n_good = close["good"] - mark["good"]
        # Goodput — THE saturation metric (PAPERS.md): completions that
        # landed inside the caller's budget, per second of the window.
        out["goodput"] = round(n_good / elapsed, 2)
        out["late"] = n - n_good
        # Deadline-miss rate: late + platform-shed (expired) work over
        # everything that asked for a deadline and resolved in-window.
        n_expired = close["expired"] - mark["expired"]
        resolved = n + n_expired
        if resolved:
            out["deadline_miss_rate"] = round(
                (out["late"] + n_expired) / resolved, 3)
    labeled = {cls for cls in close["by_class"] if cls}
    if labeled:
        # Per-priority window deltas (the --mix profiles' report): the
        # class label is the X-Priority value each request carried.
        per = {}
        for cls in sorted(labeled):
            at_close = close["by_class"].get(cls, {})
            at_open = mark["by_class"].get(
                cls, {"completed": 0, "good": 0, "failed": 0, "expired": 0})
            c = at_close.get("completed", 0) - at_open["completed"]
            g = at_close.get("good", 0) - at_open["good"]
            e = at_close.get("expired", 0) - at_open["expired"]
            entry = {
                "completed": c,
                "failed": at_close.get("failed", 0) - at_open["failed"],
                "expired": e,
            }
            if deadline_s is not None:
                entry["goodput"] = round(g / elapsed, 2)
                entry["late"] = c - g
                if c + e:
                    entry["deadline_miss_rate"] = round(
                        (entry["late"] + e) / (c + e), 3)
            per[cls] = entry
        out["by_priority"] = per
    if tenant_names is not None:
        # Per-tenant window deltas (docs/tenancy.md): who completed, who
        # ran late, and who paid the tenant-quota 429s — the bench's
        # --tenant-mix noisy-neighbor A/B reads its verdict off this.
        zero = {"offered": 0, "completed": 0, "good": 0, "failed": 0,
                "expired": 0, "quota_shed": 0}
        per_tenant = {}
        for name in sorted(close["by_tenant"]):
            at_close = close["by_tenant"][name]
            at_open = mark["by_tenant"].get(name, zero)
            entry = {k: at_close.get(k, 0) - at_open[k] for k in zero}
            g = entry.pop("good")
            if deadline_s is not None:
                entry["goodput"] = round(g / elapsed, 2)
                entry["late"] = entry["completed"] - g
            per_tenant[name] = entry
        out["by_tenant"] = per_tenant
    return out


async def run_open_loop(
    session,
    *,
    post_url: str,
    payload: bytes,
    headers: dict,
    rate: float,
    status_url_for,
    duration: float = 20.0,
    ramp: float = 2.0,
    max_inflight: int = 512,
    task_timeout: float = 120.0,
    poll_wait: float = 30.0,
    post_url_for=None,
    on_accepted=None,
    on_terminal=None,
) -> dict:
    """Drive ``post_url`` OPEN-loop at an offered ``rate`` (request starts
    per second) — the rig's load shape (ISSUE 11): unlike the closed loop,
    arrival times are scheduled by the clock, not by completions, so a
    slow platform faces the same offered rate as a fast one and the gap
    shows up as queueing/errors instead of silently lowering the load.

    Honesty contract: ``offered`` counts every scheduled start — including
    starts the CLIENT could not launch because ``max_inflight`` requests
    were already outstanding (taxonomy ``client_saturated``: the loadgen
    itself was the bottleneck; the platform never saw those). ``achieved``
    counts requests that reached a terminal outcome. The window JSON
    reports ``offered_rate`` vs ``achieved_rate`` plus the same client
    error taxonomy as the closed loop.

    ``on_accepted(task_id)`` / ``on_terminal(task_id, status)`` feed the
    rig's cross-process invariant verdict (every accepted task terminal).
    """
    import aiohttp

    offered = 0
    launched = 0
    completed = 0
    failed = 0
    expired = 0
    latencies: list[float] = []
    errors: dict[str, int] = {}
    inflight: set = set()

    def _err(kind: str) -> None:
        errors[kind] = errors.get(kind, 0) + 1

    async def one() -> None:
        t0 = time.perf_counter()
        url = post_url if post_url_for is None else post_url_for()
        nonlocal completed, failed, expired
        try:
            async with session.post(url, data=payload,
                                    headers=headers) as resp:
                if resp.status in (503, 429):
                    # Tenant-quota 429s get their own taxonomy line: the
                    # rig runs one open loop per tenant, so this count IS
                    # that tenant's shed tally in the verdict.
                    if "tenant-quota" in resp.headers.get(
                            "X-Shed-Reason", ""):
                        _err("tenant_quota_429")
                    else:
                        _err(f"backpressure_{resp.status}")
                    return
                if resp.status == 504:
                    _err("shed_504")
                    expired += 1
                    return
                if resp.status >= 400:
                    _err(f"http_{resp.status}")
                    failed += 1
                    return
                task = await resp.json()
            task_id = task["TaskId"]
        except asyncio.TimeoutError:
            _err("timeout")
            failed += 1
            return
        except aiohttp.ClientError as exc:
            _err("connect_error"
                 if isinstance(exc, aiohttp.ClientConnectorError)
                 else "transport_error")
            failed += 1
            return
        except (ValueError, KeyError, TypeError):
            _err("bad_response")
            failed += 1
            return
        if on_accepted is not None:
            on_accepted(task_id)
        deadline = t0 + task_timeout
        while True:
            try:
                async with session.get(status_url_for(task_id),
                                       params={"wait": str(int(poll_wait))},
                                       headers=headers) as resp:
                    if resp.status == 404:
                        _err("task_poll_404")
                        failed += 1
                        return
                    if resp.status >= 400:
                        # Transient poll refusal (a gateway mid-kill, a
                        # store mid-failover): back off and re-poll — the
                        # task is accepted, its verdict matters.
                        await asyncio.sleep(0.2)
                    else:
                        record = await resp.json()
                        status = record["Status"]
                        if ("failed" in status or "completed" in status
                                or "expired" in status):
                            if on_terminal is not None:
                                on_terminal(task_id, status)
                            if "failed" in status:
                                failed += 1
                            elif "completed" in status:
                                completed += 1
                                latencies.append(time.perf_counter() - t0)
                            else:
                                expired += 1
                            return
            except (aiohttp.ClientError, asyncio.TimeoutError, ValueError,
                    KeyError, TypeError):
                # A kill mid-poll is expected chaos: reconnect via the
                # balancer and keep polling until the task's own budget
                # runs out.
                _err("poll_transport")
                await asyncio.sleep(0.2)
            if time.perf_counter() > deadline:
                _err("stuck_timeout")
                failed += 1
                return

    def _reap(task: asyncio.Task) -> None:
        inflight.discard(task)

    mark: dict = {}
    close: dict = {}

    async def open_window() -> None:
        await asyncio.sleep(ramp)
        mark.update(t=time.perf_counter(), offered=offered,
                    completed=completed, failed=failed, expired=expired,
                    errors=dict(errors), n_lat=len(latencies))

    async def close_window() -> None:
        await asyncio.sleep(ramp + duration)
        close.update(t=time.perf_counter(), offered=offered,
                     completed=completed, failed=failed, expired=expired,
                     errors=dict(errors), n_lat=len(latencies))

    async def pacer() -> None:
        nonlocal offered, launched
        loop = asyncio.get_running_loop()
        t0 = loop.time()
        stop_at = t0 + ramp + duration
        while True:
            now = loop.time()
            if now >= stop_at:
                return
            due = int(rate * (now - t0)) - offered
            for _ in range(due):
                offered += 1
                if len(inflight) >= max_inflight:
                    # The CLIENT is the bottleneck: record it as such —
                    # this offered start never reached the platform.
                    _err("client_saturated")
                    continue
                task = loop.create_task(one())
                inflight.add(task)
                task.add_done_callback(_reap)
                launched += 1
            await asyncio.sleep(0.005)

    await asyncio.gather(pacer(), open_window(), close_window())
    if inflight:
        # Bounded drain so accepted tasks get their verdict; the window
        # stats were snapshotted at close time already.
        await asyncio.wait(inflight, timeout=task_timeout)
        for task in list(inflight):
            task.cancel()
        await asyncio.gather(*inflight, return_exceptions=True)

    elapsed = close["t"] - mark["t"]
    n = close["completed"] - mark["completed"]
    n_offered = close["offered"] - mark["offered"]
    window_lat = sorted(latencies[mark["n_lat"]:close["n_lat"]]) or [0.0]

    window_errors = _window_error_delta(close, mark)
    return {
        "mode": "open",
        "target_rate": rate,
        "offered": n_offered,
        "offered_rate": round(n_offered / elapsed, 2),
        "achieved_rate": round(n / elapsed, 2),
        "value": round(n / elapsed, 2),
        "completed": n,
        "failed": close["failed"] - mark["failed"],
        "expired": close["expired"] - mark["expired"],
        **_latency_percentiles(window_lat),
        "client_errors": window_errors,
        "duration_s": round(elapsed, 1),
        # Totals over the WHOLE run (ramp + window + drain) — what the
        # rig's invariant verdict reconciles against accepted TaskIds.
        "total_offered": offered,
        "total_launched": launched,
        "total_completed": completed,
        "total_failed": failed,
        "total_expired": expired,
        "total_errors": dict(errors),
    }
