"""Shared build-if-stale compiler for the native cores (``native/*.cpp``).

Both ctypes bindings (``broker/native.py``, ``taskstore/native.py``) build
their shared object on demand through this one helper so compiler flags and
staleness rules can never drift between the cores. Honors ``CXX``/
``CXXFLAGS`` like ``native/Makefile``.
"""

from __future__ import annotations

import logging
import os
import shlex
import subprocess

log = logging.getLogger("ai4e_tpu.native_build")

NATIVE_DIR = os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..", "..", "native"))
DEFAULT_FLAGS = ["-O2", "-shared", "-fPIC", "-std=c++17"]


def build_native_library(src_name: str, so_name: str,
                         force: bool = False) -> str:
    """Compile ``native/{src_name}`` into ``native/{so_name}`` if the .so is
    missing or older than the source; returns the .so path."""
    src = os.path.join(NATIVE_DIR, src_name)
    out = os.path.join(NATIVE_DIR, so_name)
    if (not force and os.path.exists(out)
            and os.path.getmtime(out) >= os.path.getmtime(src)):
        return out
    cxx = os.environ.get("CXX", "g++")
    flags = (shlex.split(os.environ["CXXFLAGS"])
             if os.environ.get("CXXFLAGS") else DEFAULT_FLAGS)
    cmd = [cxx, *flags, src, "-o", out]
    log.info("building native core: %s", " ".join(cmd))
    subprocess.run(cmd, check=True, capture_output=True)
    return out


def load_native_function(src_name: str, so_name: str, fn_name: str,
                         restype, argtypes):
    """Build-if-stale + CDLL + bind ONE function, or None when the
    toolchain can't produce it (callers keep a pure-Python fallback) —
    the shared loader for the per-request codecs (``ops/yuv.py``,
    ``ops/dct.py``). CDLL releases the GIL during the foreign call, which
    is what makes these codecs cheap on a serving host's event loop."""
    try:
        import ctypes

        lib = ctypes.CDLL(build_native_library(src_name, so_name))
        fn = getattr(lib, fn_name)
        fn.restype = restype
        fn.argtypes = argtypes
        return fn
    except Exception:  # noqa: BLE001 — fallback keeps serving
        log.exception("native %s unavailable; caller falls back to numpy",
                      so_name)
        return None
