"""Weighted backend sets — canary/blue-green traffic splitting.

The reference's Istio VirtualService tier supports weighted subsets but its
shipped routing never used them (``APIs/Charts/templates/routing.yml`` —
plain ROUND_ROBIN to one Service); model rollouts were all-or-nothing image
rolls. Here a route or dispatcher can name SEVERAL backends with weights —
e.g. 95% of traffic to the fleet, 5% to one worker serving a candidate
checkpoint — and every delivery picks independently. Combined with the
worker's hot-reload endpoint this is the full rollout story: canary one
replica, watch its per-model metrics, then reload the fleet.

One rule keeps the task plane coherent: every backend of a set must share
the same endpoint PATH (only hosts differ). The queue name, the recorded
task ``Endpoint``, and the rebase rule (``rebase_endpoint``) are all
path-derived, so a path mismatch would silently split a queue's identity.
"""

from __future__ import annotations

import random
from typing import Iterable

from ..taskstore.task import endpoint_path

Weighted = list[tuple[str, float]]


def normalize_backends(backend_uri: str | Iterable) -> Weighted:
    """One backend URI, or an iterable of ``"uri"`` / ``{"uri", "weight"}``
    / ``(uri, weight)`` entries → a validated ``[(uri, weight), ...]``.

    Weights are relative (they need not sum to anything); an entry may be 0
    (kept registered but receiving no traffic — the drained side of a
    blue/green flip); at least one weight must be positive; every URI must
    share one endpoint path."""
    if isinstance(backend_uri, str):
        return [(backend_uri, 1.0)]
    if (isinstance(backend_uri, list) and backend_uri
            and all(isinstance(e, tuple) and len(e) == 2
                    and isinstance(e[0], str) and isinstance(e[1], float)
                    for e in backend_uri)):
        # Already normalized (every producer of this exact shape ran the
        # validation below) — registration paths hand sets down through
        # several layers and must not pay or drift on re-validation. A COPY,
        # never the caller's list object: the result is stored in live
        # routes/dispatchers, and a caller mutating its own list after
        # registration must not silently rewrite routing weights (ADVICE r5).
        return list(backend_uri)
    out: Weighted = []
    for entry in backend_uri:
        if isinstance(entry, str):
            uri, weight = entry, 1.0
        elif isinstance(entry, dict):
            uri, weight = entry["uri"], float(entry.get("weight", 1.0))
        else:
            uri, weight = entry[0], float(entry[1])
        if weight < 0:
            raise ValueError(f"negative backend weight for {uri!r}")
        out.append((uri, weight))
    if not out:
        raise ValueError("backend list is empty")
    if all(w == 0 for _, w in out):
        raise ValueError("every backend has weight 0 — nothing can serve")
    paths = {endpoint_path(u) for u, _ in out}
    if len(paths) > 1:
        raise ValueError(
            "canary backends must share one endpoint path (only hosts may "
            f"differ): got {sorted(paths)}")
    return out


def pick_backend(backends: Weighted, rng: random.Random | None = None) -> str:
    """One weighted independent pick. Single-backend sets skip the RNG —
    the common deployment pays nothing for the feature existing."""
    if len(backends) == 1:
        return backends[0][0]
    uris, weights = zip(*backends)
    return (rng or random).choices(uris, weights=weights, k=1)[0]
