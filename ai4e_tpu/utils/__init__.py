from .http import SessionHolder

__all__ = ["SessionHolder"]
