from .dispatcher import AWAITING_STATUS, BACKPRESSURE_CODES, Dispatcher, DispatcherPool
from .push import PushEvent, PushTopic, SubscriptionError, WebhookDispatcher
from .queue import EndpointQueue, InMemoryBroker, Message

__all__ = [
    "AWAITING_STATUS",
    "BACKPRESSURE_CODES",
    "Dispatcher",
    "DispatcherPool",
    "EndpointQueue",
    "InMemoryBroker",
    "Message",
    "PushEvent",
    "PushTopic",
    "SubscriptionError",
    "WebhookDispatcher",
]
