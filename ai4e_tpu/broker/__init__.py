from .dispatcher import AWAITING_STATUS, BACKPRESSURE_CODES, Dispatcher, DispatcherPool
from .queue import EndpointQueue, InMemoryBroker, Message

__all__ = [
    "AWAITING_STATUS",
    "BACKPRESSURE_CODES",
    "Dispatcher",
    "DispatcherPool",
    "EndpointQueue",
    "InMemoryBroker",
    "Message",
]
