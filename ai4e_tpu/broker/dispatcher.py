"""Dispatcher — drains endpoint queues and pushes tasks to backend services.

The reference runs one Service-Bus-triggered function app per endpoint queue
(``ProcessManager/BackendQueueProcessor/BackendQueueProcessor.cs:27-81``) that
POSTs the task body to the backend URI with a ``taskId`` header and implements
backpressure-aware retry:

- backend 429 (or our 503) — backend at its concurrency cap — update the task
  to "Awaiting service availability", wait ``retry_delay``, abandon the message
  so the broker redelivers (``BackendQueueProcessor.cs:54-64``);
- other failures — complete the message (no redelivery) and fail the task
  (``:65-70``);
- success — complete; the backend drives the task's status from there.

Delivery is serial per queue by default (``BackendQueueProcessor/host.json:3-12``
pins prefetch=1, maxConcurrentCalls=1) — here that's ``concurrency=1`` —
but unlike the reference the concurrency is configurable per dispatcher, which
is how request-level fan-out to a pool of TPU workers scales.
"""

from __future__ import annotations

import asyncio
import logging

import aiohttp

from ..metrics import DEFAULT_REGISTRY, MetricsRegistry
from ..observability import ledger as hop
from ..utils.backends import normalize_backends, pick_backend
from ..utils.http import SessionHolder
from ..service.task_manager import TaskManagerBase
from ..taskstore import TaskStatus
from .queue import InMemoryBroker, Message, base_queue_name

log = logging.getLogger("ai4e_tpu.dispatcher")

# Backend saturation signals: the reference checks 429 TooManyRequests
# (BackendQueueProcessor.cs:54); our service shell emits 503 for the same
# condition (ai4e_service.py:122-125 does too) — treat both as backpressure.
# Shared by both transports (queue dispatcher here, push webhook in
# ``broker.push``) so they classify backend responses identically.
BACKPRESSURE_CODES = (429, 503)
AWAITING_STATUS = "Awaiting service availability"


def rebase_endpoint(endpoint: str, base_path: str, backend_uri: str) -> str:
    """Graft ``endpoint``'s operation tail and query onto ``backend_uri``.

    The task records the original request URI as its Endpoint
    (``request_policy.xml:15``); dispatch targets the *registered* backend
    (fresh host) with the endpoint's tail/query grafted on so the exact call
    the client made is reproduced. One rule for both transports.
    """
    from urllib.parse import urlparse
    parsed = urlparse(endpoint)  # handles bare paths too
    path = parsed.path
    base = base_path.rstrip("/")
    target = backend_uri
    if path != base and path.startswith(base + "/"):
        target = backend_uri.rstrip("/") + path[len(base):]
    if parsed.query:
        target += "?" + parsed.query
    return target


class Dispatcher:
    """Drains one endpoint queue, POSTing each task to ``backend_uri`` —
    or, with a weighted backend LIST, splitting deliveries across hosts
    (canary rollouts; ``utils/backends.py``). Each delivery picks
    independently, so a retried message may land on the other version —
    desirable: a canary that 503s doesn't strand its tasks."""

    def __init__(
        self,
        broker: InMemoryBroker,
        queue_name: str,
        backend_uri,
        task_manager: TaskManagerBase,
        retry_delay: float = 60.0,
        concurrency: int = 1,
        request_timeout: float = 300.0,
        metrics: MetricsRegistry | None = None,
        rng=None,
        result_cache=None,
        result_store=None,
        admission=None,
        resilience=None,
        orchestration=None,
        observability=None,
        tenancy=None,
    ):
        self.broker = broker
        self.queue_name = queue_name
        # The endpoint path this queue serves — equal to queue_name except
        # on shard sub-queues ("{path}#s{i}"), where dispatch-target
        # rebasing must graft operation tails against the real route path,
        # not the suffixed queue name.
        self.route_path = base_queue_name(queue_name)
        # Inference result cache (rescache/): a message whose task carries a
        # cache key is checked against it BEFORE the backend POST — a
        # redelivered/requeued/journal-restored task whose identical request
        # already completed finishes here, never re-executing on device.
        # ``result_store`` (duck-typed set_result, e.g. the platform's task
        # store) receives the cached payload so the client's result fetch
        # works exactly as on the execute path.
        self.result_cache = result_cache
        self.result_store = result_store
        # Admission controller (admission/): when set, this dispatcher's
        # delivery RTTs feed the controller's per-queue limiter (which in
        # turn drives set_concurrency — see platform_assembly), backend
        # backpressure triggers an immediate multiplicative backoff, and
        # expired work is dropped at pop time with provenance metrics.
        # Deadline DROPS themselves need no controller — any message
        # carrying deadline_at is honored (only an admission-enabled
        # gateway stamps one).
        self.admission = admission
        # Shared per-backend health model (resilience/): breaker-aware
        # backend picks (open backends ejected, their weight redistributed),
        # bounded in-delivery retries with failover to a DIFFERENT backend
        # on connection error, and 5xx-as-transient redelivery. None (the
        # default) keeps the pre-resilience delivery SEMANTICS: one
        # attempt, 5xx→permanent fail, unreachable→redeliver. (Redelivery
        # PACING is jittered-exponential either way — _redelivery_delay;
        # retry_delay is its base/first step, no longer a constant.)
        self.resilience = resilience
        # Orchestrator (orchestration/): when set (requires resilience —
        # the assembly enforces it), each delivery's backend is the
        # cheapest one predicted to finish within the message's remaining
        # deadline budget instead of a health-weighted random pick, and
        # delivered-POST RTTs feed the per-backend completion estimator.
        # None (default) keeps the resilience pick byte for byte.
        self.orchestration = orchestration
        # Request-observability hub (observability/hub.py): when set,
        # every delivery stamps hop-ledger events — popped, placement
        # outcome, delivered, retry/failover, backpressure, expiry,
        # duplicate suppression, dead-letter — onto the task's timeline.
        # None (the default) stamps nothing: the pre-observability
        # dispatcher byte for byte.
        self.observability = observability
        # Tenancy facade (tenancy/): when set alongside orchestration,
        # every successful delivery charges the message's tenant the
        # placement cost of the backend it ran on — the per-workload cost
        # accounting the per-tenant series report. None (default) charges
        # nothing: the pre-tenancy dispatcher byte for byte.
        self.tenancy = tenancy
        self._retry_budget = (resilience.new_budget()
                              if resilience is not None else None)
        self.backends = normalize_backends(backend_uri)
        # The primary (first) backend — what single-backend consumers and
        # introspection read; weighted picks use the full set.
        self.backend_uri = self.backends[0][0]
        self._rng = rng
        self.task_manager = task_manager
        self.retry_delay = retry_delay
        self.concurrency = concurrency
        self.request_timeout = request_timeout
        self.metrics = metrics or DEFAULT_REGISTRY
        self._dispatched = self.metrics.counter(
            "ai4e_dispatch_total", "Dispatch attempts by outcome")
        # Component tracer carrying this dispatcher's registry so its
        # ai4e_span_seconds series lands beside ai4e_dispatch_total in the
        # assembly's /metrics instead of the process default (AIL002);
        # exporter/sampling still follow configure_tracer live.
        from ..observability import Tracer
        self.tracer = Tracer("dispatcher", metrics=self.metrics)
        self._stop = asyncio.Event()
        self._workers: list[asyncio.Task] = []
        # Graceful scale-down debt (set_concurrency): how many delivery
        # loops should exit at their next idle point instead of being
        # cancelled mid-POST. Event-loop-only state, like _workers.
        self._excess = 0
        # Resizes before start() (or after stop()) only record the level;
        # spawning belongs to the started dispatcher's event loop.
        self._started = False
        # Delivery loops currently processing a message (vs idle in
        # receive): the concurrency actually IN USE, which is what the
        # admission limiter's Little's-law clamp compares the limit
        # against — without it an idle queue's limit would ratchet to the
        # ceiling on healthy RTTs alone, then dump that fan-out on the
        # first burst.
        self._busy = 0
        # In-flight POSTs are bounded by the worker-loop count (see
        # set_concurrency), so the pool must not add a lower cap.
        self._sessions = SessionHolder(timeout=request_timeout, limit=0)

    async def start(self) -> None:
        # Restart-safe: a demoted-then-re-promoted control plane stops and
        # later restarts its dispatchers (platform_assembly.demote_now) —
        # clear the stop latch and drop finished workers so the top-up
        # spawns live loops, not instant-exit ones.
        self._stop.clear()
        self._started = True
        self._workers = [w for w in self._workers if not w.done()]
        self._excess = 0
        # Top up, never replace: set_concurrency may have spawned loops
        # already, and replacing the list would orphan them past stop().
        loop = asyncio.get_running_loop()
        while len(self._workers) < self.concurrency:
            self._workers.append(loop.create_task(self._run(len(self._workers))))

    async def stop(self) -> None:
        self._started = False
        self._stop.set()
        for w in self._workers:
            w.cancel()
        await asyncio.gather(*self._workers, return_exceptions=True)
        await self._sessions.close()

    def set_concurrency(self, n: int) -> None:
        """Live-resize the delivery loop count — the scale surface the
        autoscaler AND the admission controller drive (the reference scales
        *pod replicas* via HPA, ``autoscaler.yaml:11-21``; here
        request-level fan-out is dispatcher loops feeding the shared
        micro-batcher, SURVEY.md §2 parallelism table row 1).

        Scale-DOWN is graceful: surplus loops finish their in-flight
        delivery and exit at the next idle point (bounded by the 1 s
        receive poll) rather than being cancelled mid-POST — the adaptive
        controller resizes this constantly, and a hard cancel would
        abandon a message whose backend call already succeeded, turning
        every downward step into a spurious redelivery. stop() still
        cancels outright (shutdown wants the lease back immediately)."""
        n = max(0, n)
        if not self._started:
            # Assembly time (the admission controller applies its initial
            # limit at registration; a standby platform registers but must
            # not dispatch): record the level — start() spawns to it.
            self.concurrency = n
            self._excess = 0
            return
        loop = asyncio.get_running_loop()
        # Prune exited loops (earlier scale-downs) so the live count — not
        # the historical list length — is what grows/shrinks.
        self._workers = [w for w in self._workers if not w.done()]
        live = len(self._workers) - self._excess
        if n == live:
            self.concurrency = n
            return
        if n > live:
            # Cancel outstanding exit debt first; only the remainder needs
            # fresh loops.
            absorbed = min(self._excess, n - live)
            self._excess -= absorbed
            while len(self._workers) - self._excess < n:
                self._workers.append(
                    loop.create_task(self._run(len(self._workers))))
        else:
            self._excess += live - n
        self.concurrency = n

    async def _run(self, worker_idx: int) -> None:
        while not self._stop.is_set():
            if self._excess > 0:
                # Graceful scale-down: retire this loop at an idle point
                # (single-threaded event loop — the decrement cannot race).
                self._excess -= 1
                return
            msg = await self.broker.receive(self.queue_name, timeout=1.0)
            if msg is None:
                continue
            self._busy += 1
            try:
                await self._dispatch_one(msg)
            except asyncio.CancelledError:
                # Scale-down / shutdown mid-dispatch: hand the message back
                # now rather than waiting out the lease.
                self.broker.abandon(msg)
                raise
            except Exception:  # noqa: BLE001 — dispatcher must never die
                log.exception("dispatch of task %s crashed; redelivering", msg.task_id)
                if not self.broker.abandon(msg):
                    # Lease-reaper path: no delivery was attempted here, so
                    # there is no target host — empty label keeps the
                    # series key set consistent with the delivery path.
                    # Terminal re-check (AIL003): a crash AFTER the task
                    # completed (e.g. complete() raced the lease reaper)
                    # must not stamp DEAD_LETTER over the completion the
                    # client may already have read.
                    self._dispatched.inc(outcome="dead_letter",
                                         queue=self.queue_name, backend="")
                    if not await self.task_manager.is_terminal(msg.task_id):
                        await self._try_update(
                            msg.task_id, TaskStatus.DEAD_LETTER,
                            TaskStatus.FAILED)
            finally:
                self._busy -= 1

    def _stamp(self, task_id: str, event: str, reason: str | None = None,
               t: float | None = None) -> None:
        """Hop-ledger stamp (observability/); no-op when the layer is
        off. The hub is fail-open — a dropped stamp never fails the
        delivery it annotates."""
        if self.observability is None:
            return
        self.observability.stamp(
            task_id, hop.ledger_event(event, "dispatcher", t=t,
                                      reason=reason))

    def _target_for(self, msg: Message,
                    exclude: tuple | list = ()) -> tuple[str, str]:
        """Dispatch target: a *registered* backend URI (fresh host — a
        journal-restored task may carry a stale one; weighted pick across a
        canary set, health-aware under resilience, deadline/cost-aware
        under orchestration) with the task endpoint's operation tail and
        query grafted on (``rebase_endpoint``). Returns ``(base, target)``
        — the base is the health-model key for outcome recording."""
        if self.orchestration is not None:
            note = None
            if self.observability is not None:
                def note(outcome: str, uri: str,
                         _tid=msg.task_id) -> None:
                    # Placement outcome + chosen backend onto the
                    # timeline: probes keep their own event name (the
                    # recovery-probe diversion is exactly what an
                    # operator hunts for — and WHICH backend was probed
                    # is the diagnostic half of that), everything else
                    # is a ``placed`` with outcome + host as reason.
                    from urllib.parse import urlparse
                    host = urlparse(uri).netloc or uri
                    self._stamp(_tid,
                                hop.PROBE if outcome == "probe"
                                else hop.PLACED,
                                reason=(host if outcome == "probe"
                                        else f"{outcome} {host}"))
            base = self.orchestration.place(
                self.backends,
                deadline_at=getattr(msg, "deadline_at", 0.0),
                priority=getattr(msg, "priority", 1),
                rng=self._rng, exclude=exclude, note=note)
        elif self.resilience is not None:
            base = self.resilience.pick(self.backends, self._rng,
                                        exclude=exclude)
        else:
            base = pick_backend(self.backends, self._rng)
        return base, rebase_endpoint(msg.endpoint, self.route_path, base)

    def _record_outcome(self, base: str, status: int | None = None,
                        failed: bool = False) -> None:
        """Feed one delivery outcome to the shared health model. A breaker
        that OPENS here also backs off the admission limiter: explicit
        evidence that a backend died outranks the latency samples the
        gradient limiter would otherwise need a whole window to believe."""
        if self.resilience is None:
            return
        opened = (self.resilience.record_failure(base) if failed
                  else self.resilience.observe_status(base, status))
        if opened and self.admission is not None:
            self.admission.scope("dispatch:" + self.queue_name).backoff()

    def _can_retry(self, attempt: int) -> bool:
        """In-delivery retry gate: attempts remaining AND retry budget —
        past either, the message falls back to broker redelivery, whose
        patience (max_delivery_count) bounds the total."""
        return (self.resilience is not None
                and attempt < self.resilience.policy.max_attempts
                and self._retry_budget.try_retry())

    async def _retry_sleep(self, attempt: int) -> None:
        from ..resilience.retry import backoff_s
        policy = self.resilience.policy
        await asyncio.sleep(backoff_s(attempt, policy.retry_base_s,
                                      policy.retry_cap_s, self._rng))

    async def _dispatch_one(self, msg: Message) -> None:
        import time as _time
        from urllib.parse import urlparse

        self._stamp(msg.task_id, hop.POPPED,
                    reason=f"delivery {msg.delivery_count}")
        if await self._drop_expired(msg):
            return
        if self.resilience is not None and await self._suppress_duplicate(msg):
            return
        if await self._complete_from_cache(msg):
            return
        if self._retry_budget is not None:
            self._retry_budget.on_request()
        tracer = self.tracer
        tried: list[str] = []
        attempt = 0
        while True:
            attempt += 1
            base, target = self._target_for(msg, exclude=tried)
            # Per-backend outcome label: the canary loop is "watch the
            # canary's error rate, then promote" — without the host
            # dimension a canary's failures would vanish into the fleet's
            # counter.
            backend = urlparse(target).netloc
            session = await self._sessions.get()
            t0 = _time.perf_counter()
            if self.orchestration is not None:
                # Queue-pressure input for the completion estimator; the
                # finally below releases it on EVERY exit of this attempt
                # (success, failure, retry-continue, cancellation).
                self.orchestration.begin(base)
            try:
                # One span per delivery attempt, keyed by TaskId; the
                # injected x-b3 headers parent the backend's endpoint span
                # to this one, so a task's dispatch → execution is a single
                # trace.
                with tracer.span("dispatch", task_id=msg.task_id,
                                 queue=self.queue_name,
                                 attempt=msg.delivery_count) as span:
                    headers = {"taskId": msg.task_id,
                               "Content-Type": msg.content_type,
                               **self._admission_headers(msg),
                               **tracer.headers()}
                    async with session.post(
                        target, data=msg.body, headers=headers,
                    ) as resp:
                        status = resp.status
                        draining = resp.headers.get("X-Draining")
                        await resp.read()
                    span.attrs["http_status"] = status
                    if not (200 <= status < 300
                            or status in BACKPRESSURE_CODES):
                        span.status = "error"
                        span.error = f"backend returned {status}"
            except (aiohttp.ClientError, asyncio.TimeoutError) as exc:
                self._record_outcome(base, failed=True)
                if (self.resilience is not None
                        and await self._suppress_duplicate(msg)):
                    # Lost-response window INSIDE the attempt loop: a
                    # timeout/disconnect can follow an execution that
                    # already completed the task (the redelivery path
                    # re-checks this at pop time; an in-delivery retry
                    # must too, or it re-executes against a worker whose
                    # completion write is unconditional).
                    return
                if self._can_retry(attempt):
                    # Failover: the next pick excludes this backend, so a
                    # multi-backend set retries on a DIFFERENT host (a
                    # single-backend set retries in place after the
                    # jittered backoff — the pod may be restarting).
                    tried.append(base)
                    self.resilience.note_failover("dispatcher")
                    self._stamp(msg.task_id, hop.FAILOVER,
                                reason=f"connect_error {backend}")
                    await self._retry_sleep(attempt)
                    continue
                # Backend unreachable — treat like saturation: the pod may
                # be restarting; broker patience (max deliveries) bounds
                # total retry.
                log.warning("backend %s unreachable (%s); will redeliver",
                            target, exc)
                await self._backpressure(msg, backend=backend)
                return
            finally:
                if self.orchestration is not None:
                    self.orchestration.end(base)

            if draining and self.resilience is not None:
                # The worker said it is LEAVING (rollout drain, not
                # saturation): eject it from placement for a TTL so the
                # redelivered task lands on a peer — saturation-neutral
                # for the breaker, which _record_outcome already ensures
                # for the 503 itself (docs/deployment.md#drain).
                self.resilience.mark_draining(base)
            self._record_outcome(base, status=status)
            if 200 <= status < 300:
                self.broker.complete(msg)
                self._stamp(msg.task_id, hop.DELIVERED, reason=backend)
                self._dispatched.inc(outcome="delivered",
                                     queue=self.queue_name, backend=backend)
                if self.orchestration is not None:
                    # Delivered round trip feeds the per-backend completion
                    # estimator (the placement's service-time evidence).
                    self.orchestration.observe(base,
                                               _time.perf_counter() - t0)
                    if self.tenancy is not None:
                        # Charge the tenant what this placement cost — at
                        # delivery, on the backend it actually ran on, so
                        # failovers bill the final host, not the intent.
                        self.tenancy.charge(getattr(msg, "tenant", ""),
                                            self.orchestration.cost_of(base))
                if self.admission is not None:
                    # Delivered-POST RTT feeds the per-queue limiter: when
                    # the worker's event loop congests, these round trips
                    # stretch and the controller narrows this dispatcher's
                    # fan-out BEFORE the worker has to start 503ing.
                    # ``_busy`` (loops actually mid-delivery) is the
                    # in-flight figure the Little's-law clamp needs — an
                    # underused queue's limit then tracks ~2× its real
                    # concurrency instead of ratcheting to the ceiling.
                    self.admission.scope(
                        "dispatch:" + self.queue_name).observe(
                        _time.perf_counter() - t0, inflight=self._busy)
                return
            if status in BACKPRESSURE_CODES:
                if self.admission is not None:
                    # Explicit saturation outranks latency evidence: shrink
                    # the fan-out multiplicatively right now, don't wait a
                    # window.
                    self.admission.scope(
                        "dispatch:" + self.queue_name).backoff()
                await self._backpressure(msg, backend=backend)
                return
            if self.resilience is not None and status >= 500:
                # Transient-class server error under resilience: retry
                # (budget-bounded, different backend when one exists), then
                # fall back to redelivery — the broker's delivery budget
                # bounds the total, and dead-letter still terminates the
                # task. 4xx stays permanent: the backend is healthy, the
                # request is not.
                if self._can_retry(attempt):
                    tried.append(base)
                    self.resilience.note_retry("dispatcher")
                    self._stamp(msg.task_id, hop.RETRY,
                                reason=f"HTTP {status} {backend}")
                    await self._retry_sleep(attempt)
                    continue
                await self._backpressure(msg, backend=backend)
                return
            # Permanent failure: complete (no redelivery) + fail the task
            # (BackendQueueProcessor.cs:65-70).
            self.broker.complete(msg)
            if (self.task_manager is not None
                    and await self.task_manager.is_terminal(msg.task_id)):
                # Re-check AFTER the POST (AIL007): the pop-time duplicate
                # guard went stale across the delivery round trip — a
                # concurrent duplicate (reaper rescue, lease-expiry
                # redelivery on another loop) can have completed the task
                # while this attempt was in flight, and its backend then
                # 4xx'd THIS attempt. Stamping `failed` now would clobber
                # the completion the client may already have read.
                self._dispatched.inc(outcome="duplicate",
                                     queue=self.queue_name, backend=backend)
                return
            self._dispatched.inc(outcome="failed", queue=self.queue_name,
                                 backend=backend)
            await self._try_update(
                msg.task_id,
                f"failed - backend returned {status}",
                TaskStatus.FAILED,
            )
            return

    def _admission_headers(self, msg: Message) -> dict:
        """Deadline/priority propagation onto the backend POST — the worker
        runs its own submit-time expiry check and priority-classed batching
        off these (``admission/deadline.py``). Absolute deadline, so
        transport time spent in the queue can never re-extend the budget."""
        deadline_at = getattr(msg, "deadline_at", 0.0)
        priority = getattr(msg, "priority", 1)
        if self.admission is None and not deadline_at and priority == 1:
            # Admission off and nothing stamped: byte-identical POST
            # headers to the pre-admission dispatcher.
            return {}
        from ..admission.deadline import propagation_headers
        return propagation_headers(deadline_at, priority)

    async def _drop_expired(self, msg: Message) -> bool:
        """Deadline check at pop time (admission/): work whose budget ran
        out while queued is completed off the broker and transitioned to
        the terminal ``expired`` status — it never reaches the backend,
        let alone the TPU. A task without a deadline (admission off, or
        the caller sent none) always dispatches."""
        import time as _time

        deadline_at = getattr(msg, "deadline_at", 0.0)
        if not deadline_at or _time.time() < deadline_at:
            return False
        from ..admission.deadline import expired_status
        from ..taskstore import TaskStatus as _TS
        self.broker.complete(msg)
        # Terminal re-check (AIL003) BEFORE any accounting: this path runs
        # ahead of duplicate suppression, so a lease-expiry redelivery of a
        # task that already COMPLETED — and whose deadline has since passed
        # — is a DUPLICATE, not an expiry. Counting it as expired (or
        # charging admission's goodput signal via note_expired) would
        # misreport it and tighten shedding on phantom evidence; writing
        # `expired` would clobber the completion the client may have read.
        if await self.task_manager.is_terminal(msg.task_id):
            self._dispatched.inc(outcome="duplicate", queue=self.queue_name,
                                 backend="")
            return True
        self._stamp(msg.task_id, hop.EXPIRED, reason="pop-time deadline")
        self._dispatched.inc(outcome="expired", queue=self.queue_name,
                             backend="")
        if self.admission is not None:
            self.admission.note_expired("dispatcher",
                                        getattr(msg, "priority", 1))
        # Awaited, not fire-and-forget: the terminal transition is what
        # wakes the task's long-poll waiters and scores goodput.
        await self._try_update(msg.task_id, expired_status("dispatcher"),
                               _TS.EXPIRED)
        return True

    async def _complete_from_cache(self, msg: Message) -> bool:
        """Serve the task from the result cache instead of dispatching, when
        its identical request already completed (rescache/). Covers the
        windows the gateway's own lookup cannot: redeliveries, reaper
        requeues, and journal-restored tasks re-seeded after a restart.
        Bypassed requests carry no cache key and always dispatch."""
        key = getattr(msg, "cache_key", "")
        if self.result_cache is None or not key:
            return False
        # count=False: the gateway already recorded this request's outcome —
        # a second count here would skew the edge hit ratio. Completions
        # from this path stay visible as dispatch_total{outcome=cache_hit}.
        found = self.result_cache.get(key, count=False)
        if found is None:
            return False
        # task_manager is None only in result-path-focused tests; this path
        # never touched it before the guard, so stay tolerant.
        if (self.task_manager is not None
                and await self.task_manager.is_terminal(msg.task_id)):
            # Terminal re-check (AIL003), after the cache consult so the
            # probe only costs on actual hits: a redelivery of a task that
            # already completed must not write "completed - served from
            # cache" over the original completion — the client would
            # observe a SECOND completion (the chaos invariant).
            self.broker.complete(msg)
            self._dispatched.inc(outcome="duplicate", queue=self.queue_name,
                                 backend="")
            return True
        if self.result_store is None:
            # Nowhere to put the payload: completing anyway would hand the
            # client a terminal task whose result fetch returns nothing —
            # a permanently lost output. Dispatch normally instead.
            return False
        payload, ctype = found
        try:
            res = self.result_store.set_result(msg.task_id, payload,
                                               content_type=ctype)
            import inspect
            if inspect.isawaitable(res):
                await res
        except Exception:  # noqa: BLE001 — a lost result is a failed serve
            log.exception("could not store cached result for task %s; "
                          "dispatching instead", msg.task_id)
            return False
        self.broker.complete(msg)
        if (self.task_manager is not None
                and await self.task_manager.is_terminal(msg.task_id)):
            # Re-check AFTER the set_result suspension (AIL007): the probe
            # above ran before the (possibly remote) result write, and a
            # concurrent path — the real backend finishing a lost-response
            # execution, the reaper failing the task — can have turned the
            # task terminal in that window. The earlier probe-then-write
            # pair was exactly the stale-guard shape this PR's analyzer
            # exists to catch; the result overwrite above is idempotent
            # (same payload under the same key), the status write is not.
            self._dispatched.inc(outcome="duplicate", queue=self.queue_name,
                                 backend="")
            return True
        self._dispatched.inc(outcome="cache_hit", queue=self.queue_name,
                             backend="")
        await self._try_update(msg.task_id, "completed - served from cache",
                               TaskStatus.COMPLETED)
        return True

    async def _suppress_duplicate(self, msg: Message) -> bool:
        """Resilience-mode redelivery suppression: a message whose task is
        ALREADY terminal (lease-expiry redelivery racing a completion, a
        duplicated publish, a delivery whose response was lost after the
        backend finished) is completed off the broker without re-POSTing —
        the backend must not execute, and the client must not see a second
        completion overwrite the one it may already have read. Closes the
        common duplicate window; a backend completing tasks should still do
        so conditionally (``update_status_if``) for the residual race where
        the duplicate pops mid-execution (docs/resilience.md)."""
        if await self.task_manager.is_terminal(msg.task_id):
            self.broker.complete(msg)
            self._stamp(msg.task_id, hop.DUPLICATE,
                        reason="redelivery of a terminal task")
            self._dispatched.inc(outcome="duplicate", queue=self.queue_name,
                                 backend="")
            return True
        return False

    def _redelivery_delay(self, msg: Message) -> float:
        """Backoff before handing a message back for redelivery: jittered
        exponential from the message's own ``delivery_count`` (base =
        ``retry_delay``, the reference's constant — now the first step),
        capped at half the lease so a retry can never outlive its own
        lease and hand the reaper a double delivery. Same half-jitter
        schedule as the in-delivery retries (``resilience.retry``)."""
        from ..resilience.retry import backoff_s
        lease = float(getattr(self.broker, "lease_seconds", 300.0) or 300.0)
        return backoff_s(msg.delivery_count, self.retry_delay, lease / 2.0,
                         self._rng)

    async def _backpressure(self, msg: Message, backend: str) -> None:
        if self.resilience is not None and await self._suppress_duplicate(msg):
            # The task turned TERMINAL between dispatch and this redelivery
            # decision — the classic lost-response window: the backend
            # executed and completed the task, then the response (or a
            # retry) failed. The unconditional AWAITING write below would
            # clobber that completed status back to created, and the
            # redelivery would then complete the task a SECOND time — the
            # exact duplicate-visible-completion the chaos invariants
            # reject. Complete the message instead; the work is done.
            return
        self._stamp(msg.task_id, hop.BACKPRESSURE, reason=backend)
        self._dispatched.inc(outcome="backpressure", queue=self.queue_name,
                             backend=backend)
        await self._try_update(msg.task_id, AWAITING_STATUS, TaskStatus.CREATED)
        await asyncio.sleep(self._redelivery_delay(msg))
        if not self.broker.abandon(msg):
            # Dead-lettered: out of delivery budget — the backend that was
            # just attempted is the one whose failures spent it; a canary
            # killing tasks must show in ITS per-backend series.
            if (self.task_manager is not None
                    and await self.task_manager.is_terminal(msg.task_id)):
                # Re-check AFTER the awaiting-write + backoff sleep
                # (AIL007): the entry guard is two suspensions stale by
                # now, and the backoff can be many seconds — the classic
                # lost-response window where the backend executed and
                # completed the task while we slept. DEAD_LETTER/FAILED
                # over that completion would be a client-visible double
                # outcome (the chaos invariant).
                self._dispatched.inc(outcome="duplicate",
                                     queue=self.queue_name, backend=backend)
                return
            self._stamp(msg.task_id, hop.DEAD_LETTER,
                        reason=f"after {msg.delivery_count} deliveries")
            self._dispatched.inc(outcome="dead_letter", queue=self.queue_name,
                                 backend=backend)
            await self._try_update(
                msg.task_id, TaskStatus.DEAD_LETTER,
                TaskStatus.FAILED)

    async def _try_update(self, task_id: str, status: str, backend: str) -> None:
        try:
            await self.task_manager.update_task_status(task_id, status,
                                                       backend_status=backend)
        except Exception:  # noqa: BLE001
            log.exception("could not update task %s to %r", task_id, status)


class DispatcherPool:
    """One dispatcher per registered endpoint — the analogue of deploying one
    BackendQueueProcessor function app per queue path
    (``deploy_backend_queue_function.sh:17-130``), minus the ops overhead:
    registration is a dict entry, not a deployment."""

    def __init__(self, broker: InMemoryBroker, task_manager: TaskManagerBase,
                 retry_delay: float = 60.0, concurrency: int = 1,
                 result_cache=None, result_store=None, admission=None,
                 resilience=None, orchestration=None, observability=None,
                 tenancy=None, metrics: MetricsRegistry | None = None):
        self.broker = broker
        self.task_manager = task_manager
        self.retry_delay = retry_delay
        self.concurrency = concurrency
        self.result_cache = result_cache
        self.result_store = result_store
        self.admission = admission
        self.resilience = resilience
        self.orchestration = orchestration
        self.observability = observability
        self.tenancy = tenancy
        # Registry the registered dispatchers count into — the assembly's
        # own, so a custom-registry platform's /metrics carries
        # ai4e_dispatch_total instead of it silently landing in the
        # process-default registry.
        self.metrics = metrics
        self.dispatchers: dict[str, Dispatcher] = {}

    def register(self, queue_name: str, backend_uri,
                 retry_delay: float | None = None,
                 concurrency: int | None = None) -> Dispatcher:
        d = Dispatcher(
            self.broker, queue_name, backend_uri, self.task_manager,
            retry_delay=self.retry_delay if retry_delay is None else retry_delay,
            concurrency=self.concurrency if concurrency is None else concurrency,
            result_cache=self.result_cache, result_store=self.result_store,
            admission=self.admission, resilience=self.resilience,
            orchestration=self.orchestration,
            observability=self.observability,
            tenancy=self.tenancy,
            metrics=self.metrics,
        )
        self.dispatchers[queue_name] = d
        return d

    async def start(self) -> None:
        for d in self.dispatchers.values():
            await d.start()

    async def stop(self) -> None:
        await asyncio.gather(*(d.stop() for d in self.dispatchers.values()))
