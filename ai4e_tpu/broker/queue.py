"""Per-endpoint durable message queues with lease/redelivery semantics.

Replaces Azure Service Bus / Event Grid as the platform's async transport
(``ProcessManager/CacheManager/CacheConnectorUpsert.cs:263-303`` publishes one
message per task to a queue named after the endpoint;
``InfrastructureDeployment/deploy_servicebus_queue.sh:28-42`` provisions one
queue per API path with max delivery count 1440). Semantics preserved:

- one logical queue per endpoint path;
- at-least-once delivery: a consumer *leases* a message (``receive``), then
  either ``complete``s it (done) or ``abandon``s it (redeliver — the
  reference's 429 path, ``BackendQueueProcessor.cs:54-64``);
- a lease that expires without complete/abandon is redelivered too (crashed
  dispatcher);
- per-message delivery count; past ``max_delivery_count`` the message is
  dead-lettered and a callback can fail the task.

The implementation is asyncio-native. The interface is deliberately small so
the C++ broker core (``native/``) can slot in behind the same methods.
"""

from __future__ import annotations

import asyncio
import itertools
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable

from ..taskstore import endpoint_path as canonical_path

# Shard sub-queue naming (taskstore/sharding.py): with a sharded task
# store, each endpoint's logical queue splits into one physical sub-queue
# per shard — "{path}#s{shard}" — so every shard gets its own dispatchers
# and one shard's outage (store failover in progress, its dispatchers
# backing off) never stalls another shard's deliveries. '#' can never
# appear in a queue path: ``endpoint_path`` strips fragments, so the
# separator is collision-free by construction.
SHARD_QUEUE_SEP = "#s"


def shard_queue_name(base: str, shard: int) -> str:
    return f"{base}{SHARD_QUEUE_SEP}{shard}"


def base_queue_name(name: str) -> str:
    """The endpoint path a (possibly shard-suffixed) queue name serves —
    what dispatch-target rebasing and depth attribution key on."""
    return name.split(SHARD_QUEUE_SEP, 1)[0]


@dataclass
class Message:
    task_id: str
    endpoint: str
    body: bytes = b""
    content_type: str = "application/json"
    enqueued_at: float = field(default_factory=time.time)
    delivery_count: int = 0
    seq: int = 0
    lease_expires: float = 0.0
    queue_name: str = ""  # resolved by the broker at publish time
    # Result-cache provenance copied from the task (rescache/): lets the
    # dispatcher serve a redelivery straight from the cache without a store
    # round trip. "" = uncacheable/opted-out (the native broker's C struct
    # has no slot for it — its messages always dispatch).
    cache_key: str = ""
    # Admission state copied from the task (admission/): the absolute
    # deadline (unix seconds; 0.0 = none) and priority class, so the
    # dispatcher can drop already-expired work at pop time — without a
    # store round trip — and label its backend POST for the worker's own
    # shedding. (The native broker's C struct has no slots for these;
    # platform assembly refuses admission=True on the native fabric.)
    deadline_at: float = 0.0
    priority: int = 1
    # Tenant scope copied from the task (tenancy/): the lane key for the
    # weighted-fair dequeue. "" = the shared default lane. (The native
    # broker's C struct has no slot for it — platform assembly refuses
    # tenancy=True on the native fabric.)
    tenant: str = ""


DeadLetterHandler = Callable[[Message], None]

# Deficit-round-robin cost of serving one message. Every message costs the
# same here — differential *placement* cost is charged downstream by the
# dispatcher through the orchestration cost model (tenancy/accounting.py);
# the queue's job is ratio fairness, and with unit cost a lane's service
# rate converges to weight/Σweights of the contended throughput.
_DRR_COST = 1.0


class EndpointQueue:
    """Single endpoint's FIFO with leases. Not thread-safe — event-loop only."""

    def __init__(self, name: str, max_delivery_count: int = 1440,
                 lease_seconds: float = 300.0,
                 dead_letter_handler: DeadLetterHandler | None = None,
                 max_dead_letters: int = 256, metrics=None, fair=None):
        self.name = name
        # Weighted-fair dequeue policy (tenancy/lanes.py) or None. When
        # set, ready messages park in per-lane FIFOs served by deficit
        # round-robin — a flooded lane fills itself, never another — and
        # ``_ready`` stays empty. When None (the default), the single-FIFO
        # hot path below is byte-for-byte the pre-tenancy behavior.
        self.fair = fair
        self.max_delivery_count = max_delivery_count
        self.lease_seconds = lease_seconds
        self.dead_letter_handler = dead_letter_handler
        # Retained dead-letter bound: the list keeps the NEWEST N message
        # objects for inspection; older ones (bodies included) are released
        # so a poisoned queue can't grow the broker without bound. The
        # total is never silently forgotten — every dead-letter increments
        # ai4e_broker_dead_letters_total{queue=} (and ``_dead_seqs`` keeps
        # every seq, ints only, so abandon() stays truthful for evicted
        # messages too).
        self.max_dead_letters = max_dead_letters
        from ..metrics import DEFAULT_REGISTRY
        self._dead_letter_total = (metrics or DEFAULT_REGISTRY).counter(
            "ai4e_broker_dead_letters_total",
            "Messages dead-lettered per queue (total ever, unlike the "
            "bounded retained list)")
        self._ready: deque[Message] = deque()
        # Seqs logically ready (mirrors _ready minus retractions): a message
        # completed after its lease expired (the reaper already requeued it)
        # is retracted by dropping its seq here and skipping it lazily at
        # receive() — no deque rebuild, every hot operation stays O(1), and
        # a retract is only possible for a seq that IS logically ready, so
        # depth accounting can never drift (a double-complete after
        # redelivery is a no-op, not a phantom retraction).
        self._ready_seqs: set[int] = set()
        self._leased: dict[int, Message] = {}
        self._waiters: deque[asyncio.Future] = deque()
        self.dead_letters: list[Message] = []
        self._dead_seqs: set[int] = set()
        # DRR state (fair mode only). Invariants the race regression pins
        # (tests/test_race_regressions.py, docs/concurrency.md): a lane key
        # is in ``_ring`` iff it is in ``_lanes``; deficits are never
        # negative and never exceed ``_DRR_COST + max quantum``; a lane's
        # deficit is dropped when the lane empties (no banking — an idle
        # tenant cannot save up a burst of scheduling credit).
        self._lanes: dict[str, deque[Message]] = {}
        self._ring: deque[str] = deque()
        self._deficit: dict[str, float] = {}

    def _dead_letter(self, msg: Message) -> None:
        self.dead_letters.append(msg)
        if (self.max_dead_letters > 0
                and len(self.dead_letters) > self.max_dead_letters):
            del self.dead_letters[0]
        self._dead_seqs.add(msg.seq)
        self._dead_letter_total.inc(queue=self.name)
        if self.dead_letter_handler is not None:
            try:
                self.dead_letter_handler(msg)
            except Exception:  # noqa: BLE001 — dead-lettering must not throw
                import logging
                logging.getLogger("ai4e_tpu.broker").exception(
                    "dead-letter handler failed for task %s", msg.task_id)

    def __len__(self) -> int:
        return len(self._ready_seqs)

    def _dead_letter_has(self, seq: int) -> bool:
        return seq in self._dead_seqs

    @property
    def in_flight(self) -> int:
        return len(self._leased)

    def _wake_one(self) -> None:
        while self._waiters:
            fut = self._waiters.popleft()
            if not fut.done():
                fut.set_result(None)
                return

    def put(self, msg: Message) -> None:
        self._requeue(msg)
        self._wake_one()

    def _requeue(self, msg: Message) -> None:
        """Make a message logically ready (no waiter wake — ``put`` wakes,
        the lease reaper deliberately does not, exactly as before)."""
        if self.fair is not None:
            key = self.fair.lane_of(msg)
            lane = self._lanes.get(key)
            if lane is None:
                lane = self._lanes[key] = deque()
                self._ring.append(key)
            lane.append(msg)
        else:
            self._ready.append(msg)
        self._ready_seqs.add(msg.seq)

    def _pop_ready(self) -> Message | None:
        """Next message to lease, or None if nothing is logically ready.
        Retracted seqs (see ``__init__``) are skipped lazily in both modes."""
        if self.fair is not None:
            return self._pop_fair()
        while self._ready:
            msg = self._ready.popleft()
            if msg.seq not in self._ready_seqs:
                continue
            return msg
        return None

    def _pop_fair(self) -> Message | None:
        """Deficit round-robin across per-tenant lanes.

        Single-pop variant: visit the lane at the ring head; if its deficit
        covers one message, serve it and keep the ring position (the lane
        may have credit for more); otherwise credit the lane its quantum
        (its LIVE weight — read from the policy per visit, so a registry
        update rebalances the very next decision) and rotate. Terminates
        because every lane's quantum has a positive floor
        (tenancy/lanes.py min_quantum), so the head lane's deficit reaches
        ``_DRR_COST`` in a bounded number of rotations.
        """
        while self._ring:
            key = self._ring[0]
            lane = self._lanes[key]
            while lane and lane[0].seq not in self._ready_seqs:
                lane.popleft()  # retracted — same lazy skip as FIFO mode
            if not lane:
                # Lane drained: drop it from the ring and FORGET its
                # deficit (no banking across idle periods).
                self._ring.popleft()
                del self._lanes[key]
                self._deficit.pop(key, None)
                continue
            credit = self._deficit.get(key, 0.0)
            if credit >= _DRR_COST:
                self._deficit[key] = credit - _DRR_COST
                return lane.popleft()
            self._deficit[key] = credit + self.fair.quantum(key)
            self._ring.rotate(-1)
        return None

    def lane_depths(self) -> dict[str, int]:
        """Logically-ready depth per lane (fair mode; {} otherwise) —
        introspection for tests and the rig verdict, not the hot path."""
        depths = {key: sum(1 for m in lane if m.seq in self._ready_seqs)
                  for key, lane in self._lanes.items()}
        return {key: n for key, n in depths.items() if n}

    def deficits(self) -> dict[str, float]:
        """Snapshot of DRR deficit counters — the race regression asserts
        conservation (never negative, bounded by cost + max quantum)."""
        return dict(self._deficit)

    async def receive(self, timeout: float | None = None) -> Message | None:
        """Lease the next message; None on timeout."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            self._reap_expired_leases()
            msg = self._pop_ready()
            if msg is not None:
                self._ready_seqs.discard(msg.seq)
                msg.delivery_count += 1
                msg.lease_expires = time.time() + self.lease_seconds
                self._leased[msg.seq] = msg
                return msg
            fut: asyncio.Future = asyncio.get_running_loop().create_future()
            self._waiters.append(fut)
            try:
                remaining = None if deadline is None else max(0.0, deadline - time.monotonic())
                await asyncio.wait_for(fut, remaining)
            except asyncio.TimeoutError:
                if fut in self._waiters:
                    self._waiters.remove(fut)
                return None

    def complete(self, msg: Message) -> None:
        if self._leased.pop(msg.seq, None) is None:
            # Lease expired mid-processing and the reaper requeued the
            # message; retract it (drop from the logically-ready set) so a
            # successfully-processed message is not delivered again. If the
            # message was already re-leased or dead-lettered the seq is not
            # in the set and this is a no-op.
            self._ready_seqs.discard(msg.seq)

    def abandon(self, msg: Message) -> bool:
        """Return the message for redelivery. False (dead-lettered) once the
        delivery count is exhausted — ≈24 h of patience at the reference's
        60 s retry delay (setup_env.sh:65,74)."""
        if self._leased.pop(msg.seq, None) is None:
            # Lease already expired: the reaper has requeued (or
            # dead-lettered) the message; re-appending here would duplicate
            # delivery and double-burn the delivery budget.
            return not self._dead_letter_has(msg.seq)
        if msg.delivery_count >= self.max_delivery_count:
            self._dead_letter(msg)
            return False
        self.put(msg)
        return True

    def _reap_expired_leases(self) -> None:
        now = time.time()
        expired = [m for m in self._leased.values() if m.lease_expires <= now]
        for msg in expired:
            del self._leased[msg.seq]
            if msg.delivery_count >= self.max_delivery_count:
                self._dead_letter(msg)
            else:
                self._requeue(msg)


class InMemoryBroker:
    """Queue manager: one ``EndpointQueue`` per registered endpoint path.

    ``publish`` is the store's publisher hook (the reference couples them the
    same way: CacheConnectorUpsert publishes on upsert,
    ``CacheConnectorUpsert.cs:178-202``). The store calls publishers *after*
    releasing its own lock, on whatever thread ran the upsert — so the queue
    map is guarded by a lock here and the enqueue itself is handed to the
    broker's event loop via ``call_soon_threadsafe``.

    Routing: a task whose endpoint path extends a registered queue's path
    (operation tails, query params) lands on the longest-prefix-matching
    queue — mirroring the reference's one-queue-per-API (not per-operation)
    layout (``deploy_servicebus_queue.sh:28-42``).
    """

    def __init__(self, max_delivery_count: int = 1440,
                 lease_seconds: float = 300.0,
                 max_dead_letters: int = 256, metrics=None,
                 shard_router=None, fair=None):
        self.max_delivery_count = max_delivery_count
        self.lease_seconds = lease_seconds
        self.max_dead_letters = max_dead_letters
        self._metrics = metrics
        # Weighted-fair lane policy (tenancy/lanes.py), handed to every
        # queue — including per-shard sub-queues, so fairness holds inside
        # each shard's drain independently (the noisy-neighbor chaos
        # scenario checks invariants per shard for exactly this reason).
        self._fair = fair
        # Shard router (``shard_router(task_id) -> shard index``): when set,
        # publish lands each message on its task's per-shard sub-queue
        # (``shard_queue_name``) instead of the endpoint's base queue —
        # per-shard dispatchers then drain independently. Redelivery is
        # shard-aware by construction: abandon/lease-expiry return a message
        # to the sub-queue it lives on. A message whose task was rebalanced
        # mid-flight drains from the OLD shard's sub-queue once more —
        # placement staleness only; its store writes route by ring.
        self._shard_router = shard_router
        self._queues: dict[str, EndpointQueue] = {}
        self._queues_lock = threading.Lock()
        self._seq = itertools.count(1)
        self._loop: asyncio.AbstractEventLoop | None = None
        self._dead_letter_handler: DeadLetterHandler | None = None

    def bind_loop(self, loop: asyncio.AbstractEventLoop | None = None) -> None:
        self._loop = loop or asyncio.get_event_loop()

    def set_dead_letter_handler(self, handler: DeadLetterHandler | None) -> None:
        """Callback for messages that exhaust their delivery budget in any
        path (explicit abandon or lease-expiry reaping) — the platform wires
        this to fail the task so it never sits non-terminal forever."""
        self._dead_letter_handler = handler
        with self._queues_lock:
            for q in self._queues.values():
                q.dead_letter_handler = handler

    def register_queue(self, name: str) -> None:
        """Pre-create a queue so prefix routing can target it (parity with
        the native broker's explicit registration)."""
        self.queue(name)

    def queue(self, name: str) -> EndpointQueue:
        with self._queues_lock:
            q = self._queues.get(name)
            if q is None:
                q = self._queues[name] = EndpointQueue(
                    name, self.max_delivery_count, self.lease_seconds,
                    dead_letter_handler=self._dead_letter_handler,
                    max_dead_letters=self.max_dead_letters,
                    metrics=self._metrics, fair=self._fair)
            return q

    def queue_names(self) -> list[str]:
        with self._queues_lock:
            return sorted(self._queues)

    def depths(self) -> dict[str, int]:
        with self._queues_lock:
            return {name: len(q) for name, q in self._queues.items()}

    def resolve_queue_name(self, endpoint: str) -> str:
        """Longest registered queue path that prefixes the endpoint path;
        falls back to the exact path (a queue is created on demand). Shard
        sub-queues never match — routing picks the BASE queue, and publish
        appends the task's shard suffix itself."""
        path = canonical_path(endpoint)
        with self._queues_lock:
            candidates = [n for n in self._queues
                          if SHARD_QUEUE_SEP not in n
                          and (path == n
                               or path.startswith(n.rstrip("/") + "/"))]
        return max(candidates, key=len) if candidates else path

    # -- publish side ------------------------------------------------------

    def publish(self, task) -> None:
        """Store publisher hook: enqueue a dispatch message for the task.

        Callable from any thread; the enqueue itself happens on the broker's
        event loop.
        """
        queue_name = self.resolve_queue_name(task.endpoint)
        if self._shard_router is not None:
            queue_name = shard_queue_name(queue_name,
                                          self._shard_router(task.task_id))
        msg = Message(task_id=task.task_id, endpoint=task.endpoint,
                      body=task.body,
                      content_type=getattr(task, "content_type",
                                           "application/json"),
                      seq=next(self._seq),
                      queue_name=queue_name,
                      cache_key=getattr(task, "cache_key", ""),
                      deadline_at=getattr(task, "deadline_at", 0.0),
                      priority=getattr(task, "priority", 1),
                      tenant=getattr(task, "tenant", ""))
        loop = self._loop
        try:
            running = asyncio.get_running_loop()
        except RuntimeError:
            running = None
        if loop is None or loop is running:
            self.queue(msg.queue_name).put(msg)
        else:
            loop.call_soon_threadsafe(self.queue(msg.queue_name).put, msg)

    # -- consume side ------------------------------------------------------

    async def receive(self, queue_name: str, timeout: float | None = None) -> Message | None:
        return await self.queue(queue_name).receive(timeout)

    def complete(self, msg: Message) -> None:
        self.queue(msg.queue_name).complete(msg)

    def abandon(self, msg: Message) -> bool:
        return self.queue(msg.queue_name).abandon(msg)
