"""Push (webhook) transport — the Event Grid half of the pluggable transport.

The reference supports two async transports selected by ``TRANSPORT_TYPE``
(``InfrastructureDeployment/setup_env.sh:11``, ``deploy_infrastructure.sh:13-27``):

- ``queue``     — Service Bus queues drained by BackendQueueProcessor
  (our ``broker.queue`` + ``broker.dispatcher``);
- ``eventgrid`` — CacheConnectorUpsert publishes each task to an Event Grid
  topic (``CacheConnectorUpsert.cs:234-261``); Event Grid *pushes* the event to
  the BackendWebhook function, which validates the subscription handshake and
  forwards the payload to the backend URI (``BackendWebhook.cs:29-90``),
  passing 429 through so the grid retries with backoff (``:69-72``); delivery
  policy is TTL 5 min / 3 attempts (``deploy_event_grid_subscription.sh:37``).

This module is that second transport, re-designed in-repo:

- ``PushTopic``         — the Event Grid topic: accepts published tasks,
  pushes events to HTTP subscribers concurrently (bounded by an in-flight
  delivery ``window``, like Event Grid's parallel delivery), owns the
  retry/backoff/TTL policy and the subscription-validation handshake.
  Task events ship in **binary content mode** (metadata headers + raw
  body — the CloudEvents binary HTTP mode Event Grid also speaks);
- ``WebhookDispatcher`` — the BackendWebhook function: an aiohttp app that
  answers the validation handshake, rebases each event's subject onto the
  registered backend, POSTs the body with the ``taskId`` header, and maps
  backend saturation (429/503) back to 429 so the topic retries.

Both sides speak plain HTTP, so the topic and the webhook can run in separate
processes/hosts exactly like the reference's Functions apps.
"""

from __future__ import annotations

import asyncio
import json
import logging
import secrets
import threading
import time
from dataclasses import dataclass, field

import aiohttp
from aiohttp import web

from ..metrics import DEFAULT_REGISTRY, MetricsRegistry
from ..taskstore import TaskStatus, endpoint_path
from ..utils.backends import normalize_backends, pick_backend
from ..utils.http import SessionHolder
from .dispatcher import AWAITING_STATUS, BACKPRESSURE_CODES, rebase_endpoint

log = logging.getLogger("ai4e_tpu.broker.push")

TASK_EVENT = "ai4e.task.created"
VALIDATION_EVENT = "ai4e.subscription.validation"

# Binary content mode (the CloudEvents "binary" HTTP mode Event Grid also
# speaks): event metadata rides headers, the task body rides the HTTP body
# RAW. The structured JSON envelope decodes the body surrogateescape and
# escapes it into a JSON string — for the image configs' ~100-200 kB binary
# payloads that is megabytes/s of pure (de)escaping per hop, measured as the
# r3 push-vs-queue 3x gap (bench_results/r3-tpu/landcover_push.json). Task
# events default to binary mode; the validation handshake and any external
# publisher keep the structured envelope (the webhook accepts both).
HDR_EVENT_ID = "X-AI4E-Event-Id"
HDR_EVENT_SUBJECT = "X-AI4E-Event-Subject"
HDR_EVENT_TYPE = "X-AI4E-Event-Type"
HDR_EVENT_TIME = "X-AI4E-Event-Time"
# Delivery-attempt ordinal (1-based). Lets the webhook treat a RETRY
# differently from a first delivery: a retry can trail an execution whose
# response was lost, so the webhook probes task terminality before
# re-forwarding (the queue dispatcher's duplicate-suppression analogue)
# while first deliveries stay probe-free on the hot path.
HDR_EVENT_ATTEMPT = "X-AI4E-Event-Attempt"


@dataclass
class PushEvent:
    """Event envelope — the shape CacheConnectorUpsert publishes:
    ``{Id: taskId, Subject: endpoint, Data: body}`` (``CacheConnectorUpsert.cs:245-249``)."""

    id: str                    # task id
    subject: str               # the task's endpoint (original request URI)
    data: bytes
    content_type: str = "application/json"
    event_type: str = TASK_EVENT
    event_time: float = field(default_factory=time.time)
    attempts: int = 0

    def to_wire(self) -> dict:
        return {
            "Id": self.id,
            "Subject": self.subject,
            "EventType": self.event_type,
            "EventTime": self.event_time,
            "ContentType": self.content_type,
            "Data": self.data.decode("utf-8", errors="surrogateescape"),
        }

    @classmethod
    def from_wire(cls, rec: dict) -> "PushEvent":
        return cls(
            id=rec.get("Id", ""),
            subject=rec.get("Subject", ""),
            data=rec.get("Data", "").encode("utf-8", errors="surrogateescape"),
            content_type=rec.get("ContentType", "application/json"),
            event_type=rec.get("EventType", TASK_EVENT),
            event_time=rec.get("EventTime", time.time()),
        )

    def to_headers(self) -> dict[str, str]:
        """Binary-content-mode metadata (body ships raw as the HTTP body).

        The subject is an endpoint path + query string, which may contain
        non-ASCII — and aiohttp refuses non-latin-1 header values, so an
        unencoded subject would fail EVERY delivery attempt until the TTL
        dead-letters a task the structured envelope could deliver fine.
        Percent-encode it (RFC 8187 spirit); ``from_headers`` decodes, so
        the round trip is exact for every subject including ones that
        already contain ``%``."""
        from urllib.parse import quote
        return {
            HDR_EVENT_ID: self.id,
            HDR_EVENT_SUBJECT: quote(self.subject, safe="/:?=&"),
            HDR_EVENT_TYPE: self.event_type,
            HDR_EVENT_TIME: repr(self.event_time),
            "Content-Type": self.content_type or "application/octet-stream",
        }

    def headers_for_attempt(self, attempt: int) -> dict[str, str]:
        """Delivery headers stamped with the attempt ordinal (1-based)."""
        return {**self.to_headers(), HDR_EVENT_ATTEMPT: str(attempt)}

    @classmethod
    def from_headers(cls, headers, body: bytes) -> "PushEvent":
        try:
            event_time = float(headers.get(HDR_EVENT_TIME, ""))
        except ValueError:
            event_time = time.time()
        try:
            attempts = int(headers.get(HDR_EVENT_ATTEMPT, "0"))
        except ValueError:
            attempts = 0
        from urllib.parse import unquote
        return cls(
            id=headers.get(HDR_EVENT_ID, ""),
            subject=unquote(headers.get(HDR_EVENT_SUBJECT, "")),
            data=body,
            content_type=headers.get("Content-Type",
                                     "application/octet-stream"),
            event_type=headers.get(HDR_EVENT_TYPE, TASK_EVENT),
            event_time=event_time,
            attempts=attempts,
        )


class SubscriptionError(RuntimeError):
    pass


@dataclass
class _Subscription:
    name: str
    url: str


class PushTopic:
    """Event topic with push delivery, retry/backoff, TTL, and handshake.

    Delivery policy defaults mirror the reference's Event Grid subscription:
    ``--event-ttl 5`` minutes, ``--max-delivery-attempts 3``
    (``deploy_event_grid_subscription.sh:37``). ``retry_delay`` is the base of
    an exponential backoff between attempts (Event Grid's internal schedule).

    ``publish`` has the same contract as ``InMemoryBroker.publish`` — callable
    from any thread; delivery happens on the bound event loop — so the task
    store can treat either transport as its publisher hook.
    """

    def __init__(self, ttl_seconds: float = 300.0, max_attempts: int = 3,
                 retry_delay: float = 10.0, window: int = 256,
                 metrics: MetricsRegistry | None = None):
        self.ttl_seconds = ttl_seconds
        self.max_attempts = max_attempts
        self.retry_delay = retry_delay
        # In-flight delivery window per topic (VERDICT r3 #4): Event Grid
        # delivers concurrently; this bounds how many POSTs are on the wire
        # at once. The session itself is unbounded (limit=0) — the window is
        # the cap, not a hidden 100-connection pool.
        self._window = asyncio.Semaphore(max(1, window))
        self.metrics = metrics or DEFAULT_REGISTRY
        self._delivered = self.metrics.counter(
            "ai4e_push_deliveries_total", "Push-transport deliveries by outcome")
        self._pending = self.metrics.gauge(
            "ai4e_push_pending", "Push deliveries in flight")
        self._subscriptions: list[_Subscription] = []
        self._loop: asyncio.AbstractEventLoop | None = None
        self._sessions = SessionHolder(limit=0)
        self._tasks: set[asyncio.Task] = set()
        self._dead_letter_handler = None
        self._closed = False
        # Events published before the loop is bound / the first subscription
        # validates are buffered, not refused — the same contract as
        # InMemoryBroker.publish (a gateway may accept a task in the window
        # between serving and platform.start()).
        self._backlog: list[PushEvent] = []
        self._backlog_lock = threading.Lock()

    def bind_loop(self, loop: asyncio.AbstractEventLoop | None = None) -> None:
        self._loop = loop or asyncio.get_event_loop()

    def set_dead_letter_handler(self, handler) -> None:
        """Called with a ``PushEvent`` whose delivery budget/TTL is exhausted
        — the platform fails the task so it never sits non-terminal (the
        reference's grid events just expire; SURVEY.md §5 failure handling)."""
        self._dead_letter_handler = handler

    async def subscribe(self, name: str, url: str) -> None:
        """Register a webhook subscriber after a validation handshake: POST a
        validation event bearing a one-time code; the subscriber must echo it
        back as ``{"validationResponse": code}`` (the Event Grid
        ``SubscriptionValidationEvent`` contract ``BackendWebhook.cs:47-55``)."""
        code = secrets.token_hex(16)
        event = PushEvent(id=code, subject="", data=b"",
                          event_type=VALIDATION_EVENT)
        envelope = [dict(event.to_wire(), ValidationCode=code)]
        session = await self._sessions.get()
        try:
            async with session.post(url, json=envelope) as resp:
                if resp.status != 200:
                    raise SubscriptionError(
                        f"validation handshake to {url} returned {resp.status}")
                payload = await resp.json()
        except aiohttp.ClientError as exc:
            raise SubscriptionError(f"subscriber {url} unreachable: {exc}") from exc
        if payload.get("validationResponse") != code:
            raise SubscriptionError(
                f"subscriber {url} echoed a bad validation code")
        self._subscriptions.append(_Subscription(name=name, url=url))
        log.info("push subscription %r -> %s validated", name, url)
        self._flush_backlog()

    def _flush_backlog(self) -> None:
        """Deliver events buffered before the first subscription validated.
        Runs on the event loop (subscribe is a coroutine)."""
        with self._backlog_lock:
            backlog, self._backlog = self._backlog, []
        for event in backlog:
            self._spawn(event)

    # -- publish side (store publisher hook) --------------------------------

    def publish(self, task) -> None:
        if self._closed:
            raise RuntimeError("push topic is closed")
        event = PushEvent(
            id=task.task_id, subject=task.endpoint, data=task.body,
            content_type=getattr(task, "content_type", "application/json"))
        loop = self._loop
        with self._backlog_lock:
            if loop is None or not self._subscriptions:
                self._backlog.append(event)
                return
        try:
            running = asyncio.get_running_loop()
        except RuntimeError:
            running = None
        if loop is running:
            self._spawn(event)
        else:
            loop.call_soon_threadsafe(self._spawn, event)

    def _spawn(self, event: PushEvent) -> None:
        t = asyncio.get_running_loop().create_task(self._deliver(event))
        self._tasks.add(t)
        t.add_done_callback(self._tasks.discard)
        self._pending.inc()
        t.add_done_callback(lambda _t: self._pending.dec())

    async def _deliver(self, event: PushEvent) -> None:
        """Push the event to every subscription (the reference has exactly one
        BackendWebhook subscription; fan-out is supported anyway), retrying
        each independently with exponential backoff within the TTL."""
        await asyncio.gather(*(self._deliver_to(sub, event)
                               for sub in list(self._subscriptions)))

    async def _deliver_to(self, sub: _Subscription, event: PushEvent) -> None:
        deadline = event.event_time + self.ttl_seconds
        attempts = 0
        session = await self._sessions.get()
        while True:
            attempts += 1
            try:
                # Binary content mode for task events (headers + raw body);
                # the structured envelope only when an event type needs the
                # JSON shape (validation is sent by subscribe, not here).
                async with self._window:
                    async with session.post(
                            sub.url, data=event.data,
                            headers=event.headers_for_attempt(
                                attempts)) as resp:
                        status = resp.status
                        await resp.read()
                if 200 <= status < 300:
                    self._delivered.inc(outcome="delivered", subscription=sub.name)
                    return
            except (aiohttp.ClientError, asyncio.TimeoutError) as exc:
                log.warning("push to %s failed (%s); attempt %d",
                            sub.url, exc, attempts)
            if attempts >= self.max_attempts or time.time() >= deadline:
                break
            # Exponential backoff, clipped so we never sleep past the TTL.
            delay = min(self.retry_delay * (2 ** (attempts - 1)),
                        max(0.0, deadline - time.time()))
            self._delivered.inc(outcome="retry", subscription=sub.name)
            await asyncio.sleep(delay)
            if time.time() >= deadline:
                break
        self._delivered.inc(outcome="dead_letter", subscription=sub.name)
        event.attempts = attempts
        if self._dead_letter_handler is not None:
            try:
                self._dead_letter_handler(event)
            except Exception:  # noqa: BLE001 — dead-lettering must not throw
                log.exception("push dead-letter handler failed for %s", event.id)

    # -- lifecycle ----------------------------------------------------------

    @property
    def pending(self) -> int:
        return len(self._tasks)

    async def drain(self, timeout: float = 10.0) -> None:
        if self._tasks:
            await asyncio.wait(list(self._tasks), timeout=timeout)

    async def aclose(self) -> None:
        self._closed = True
        for t in list(self._tasks):
            t.cancel()
        if self._tasks:
            await asyncio.gather(*self._tasks, return_exceptions=True)
        await self._sessions.close()


class WebhookDispatcher:
    """The BackendWebhook function as an aiohttp app.

    Routes: ``POST /api/events`` receives either a binary-content-mode event
    (``X-AI4E-Event-*`` headers + raw body) or a JSON array of structured
    event envelopes.
    A validation event is answered inline with ``{"validationResponse": code}``
    (``BackendWebhook.cs:47-55``). A task event is forwarded: the event
    subject (the task's original endpoint) is rebased onto the registered
    backend for its API prefix, then POSTed with the ``taskId`` header
    (``BackendWebhook.cs:57-67``). Backend saturation (429/503) comes back as
    429 so the topic retries with backoff (``:69-72``); other backend failures
    are acknowledged (no retry) and the task is failed — the queue
    dispatcher's permanent-failure rule (``BackendQueueProcessor.cs:65-70``).
    """

    def __init__(self, task_manager, metrics: MetricsRegistry | None = None,
                 request_timeout: float = 300.0):
        self.task_manager = task_manager
        self.metrics = metrics or DEFAULT_REGISTRY
        self._forwarded = self.metrics.counter(
            "ai4e_webhook_forwards_total", "Webhook forwards by outcome")
        # Component tracer carrying this webhook's registry so its
        # ai4e_span_seconds series lands in the assembly's /metrics, not
        # the process default (AIL002); exporter/sampling still follow
        # configure_tracer live.
        from ..observability import Tracer
        self.tracer = Tracer("webhook", metrics=self.metrics)
        # queue path prefix -> weighted backend set (utils/backends.py)
        self._routes: dict[str, list] = {}
        # In-flight bounded by the topic's delivery window, not a hidden
        # 100-connection client pool.
        self._sessions = SessionHolder(timeout=request_timeout, limit=0)
        self.app = web.Application(client_max_size=1024**3)
        self.app.router.add_post("/api/events", self._handle)
        self.app.router.add_get("/healthz", self._health)
        self.app.on_cleanup.append(self._cleanup)

    def add_route(self, api_prefix: str, backend_uri) -> None:
        """Map an API path prefix to the backend it dispatches to — the
        per-queue backend config of ``deploy_backend_queue_function.sh``,
        as a dict entry. A weighted LIST splits deliveries across hosts
        (canary; same semantics as the queue dispatcher)."""
        self._routes[endpoint_path(api_prefix)] = normalize_backends(
            backend_uri)

    def _target_for(self, subject: str) -> str | None:
        """Rebase the event subject onto the registered backend: longest
        registered prefix wins, then the shared ``rebase_endpoint`` rule
        grafts the operation tail and query on — the queue dispatcher and
        the webhook must target identically."""
        from urllib.parse import urlparse
        path = urlparse(subject).path
        candidates = [p for p in self._routes
                      if path == p or path.startswith(p.rstrip("/") + "/")]
        if not candidates:
            return None
        base = max(candidates, key=len)
        return rebase_endpoint(subject, base, pick_backend(self._routes[base]))

    async def _handle(self, request: web.Request) -> web.Response:
        if HDR_EVENT_TYPE in request.headers:
            # Binary content mode: one TASK event, metadata in headers, body
            # raw (no surrogateescape/JSON-escape round trip on binary
            # payloads). The validation handshake stays on the structured
            # envelope (subscribe() sends it that way).
            event = PushEvent.from_headers(request.headers,
                                           await request.read())
            return web.Response(status=await self._forward(event))
        try:
            envelope = await request.json()
        except json.JSONDecodeError:
            return web.Response(status=400, text="bad event envelope")
        if not isinstance(envelope, list):
            envelope = [envelope]

        worst_status = 200
        validation_code = None
        for rec in envelope:
            if rec.get("EventType") == VALIDATION_EVENT:
                # Handshake (BackendWebhook.cs:47-55). Don't short-circuit:
                # a mixed envelope's task events must still be forwarded, or
                # the publisher would see 200 and never redeliver them.
                validation_code = rec.get("ValidationCode", "")
                continue
            status = await self._forward(PushEvent.from_wire(rec))
            worst_status = max(worst_status, status)
        if worst_status == 200 and validation_code is not None:
            return web.json_response({"validationResponse": validation_code})
        return web.Response(status=worst_status)

    async def _forward(self, event: PushEvent) -> int:
        if event.attempts > 1 and await self.task_manager.is_terminal(
                event.id):
            # Terminal re-check (AIL003) — the push transport's analogue of
            # the queue dispatcher's duplicate suppression: a RETRIED
            # delivery can trail an execution whose response was lost, so
            # re-forwarding would re-execute on the backend and the
            # AWAITING/failed writes below would clobber the completion the
            # client may already have read (the PR 3 double-completion
            # class, which the queue side fixed and this side had open).
            # First deliveries (attempts <= 1) skip the probe — no store
            # round trip on the hot path; a duplicated PUBLISH of a
            # finished task is still caught at the service shell's
            # adoption guard, and every failure-path write below re-checks
            # terminality itself.
            self._forwarded.inc(outcome="duplicate")
            return 200
        target = self._target_for(event.subject)
        if target is None:
            self._forwarded.inc(outcome="unroutable")
            if not await self.task_manager.is_terminal(event.id):
                await self._try_update(
                    event.id,
                    f"failed - no backend route for {event.subject}",
                    TaskStatus.FAILED)
            return 200  # ack: retrying an unroutable event cannot help
        from urllib.parse import urlparse
        backend = urlparse(target).netloc  # canary observability dimension
        tracer = self.tracer
        session = await self._sessions.get()
        try:
            with tracer.span("webhook_dispatch", task_id=event.id) as span:
                headers = {"taskId": event.id,
                           "Content-Type": event.content_type,
                           **tracer.headers()}
                async with session.post(target, data=event.data,
                                        headers=headers) as resp:
                    status = resp.status
                    await resp.read()
                span.attrs["http_status"] = status
        except (aiohttp.ClientError, asyncio.TimeoutError) as exc:
            # Backend unreachable — let the topic retry (pod may be starting).
            log.warning("webhook backend %s unreachable: %s", target, exc)
            self._forwarded.inc(outcome="unreachable", backend=backend)
            return 429
        if 200 <= status < 300:
            self._forwarded.inc(outcome="delivered", backend=backend)
            return 200
        if status in BACKPRESSURE_CODES:
            # Saturated backend: mark awaiting, pass 429 through so the
            # topic's backoff schedule drives the retry (BackendWebhook.cs:69-72).
            # Cold path, so the terminal probe is affordable here: the
            # unconditional AWAITING write was the push side's status
            # clobber (AIL003).
            self._forwarded.inc(outcome="backpressure", backend=backend)
            if not await self.task_manager.is_terminal(event.id):
                await self._try_update(event.id, AWAITING_STATUS,
                                       TaskStatus.CREATED)
            return 429
        self._forwarded.inc(outcome="failed", backend=backend)
        if not await self.task_manager.is_terminal(event.id):
            await self._try_update(event.id,
                                   f"failed - backend returned {status}",
                                   TaskStatus.FAILED)
        return 200  # permanent failure: ack, no redelivery

    async def _try_update(self, task_id: str, status: str, backend: str) -> None:
        try:
            await self.task_manager.update_task_status(
                task_id, status, backend_status=backend)
        except Exception:  # noqa: BLE001
            log.exception("could not update task %s to %r", task_id, status)

    async def _health(self, _: web.Request) -> web.Response:
        return web.json_response({"status": "healthy",
                                  "routes": sorted(self._routes)})

    async def _cleanup(self, _app) -> None:
        await self._sessions.close()
