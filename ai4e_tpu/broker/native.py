"""ctypes bindings for the native broker core (``native/broker_core.cpp``).

``NativeBroker`` implements the same surface as ``InMemoryBroker`` (publish /
receive / complete / abandon / depths / dead-letter handler), backed by the
C++ engine: publishes and queue bookkeeping run without the GIL, and blocking
receives park on a C++ condition variable in a worker thread instead of an
asyncio future. Drop-in for ``LocalPlatform`` via
``PlatformConfig(native_broker=True)``.
"""

from __future__ import annotations

import asyncio
import ctypes
import logging
from concurrent.futures import ThreadPoolExecutor

from ..taskstore import endpoint_path as canonical_path
from .queue import DeadLetterHandler, Message

log = logging.getLogger("ai4e_tpu.broker.native")

_SO_NAME = "libbroker_core.so"


class _MessageView(ctypes.Structure):
    _fields_ = [
        ("seq", ctypes.c_uint64),
        ("delivery_count", ctypes.c_uint32),
        ("task_id", ctypes.c_char_p),
        ("endpoint", ctypes.c_char_p),
        ("content_type", ctypes.c_char_p),
        ("body", ctypes.POINTER(ctypes.c_uint8)),
        ("body_len", ctypes.c_uint64),
        ("owner", ctypes.c_void_p),
    ]


def build_library(force: bool = False) -> str:
    """Compile the broker core if the .so is missing/stale; returns its path."""
    from ..utils.native_build import build_native_library
    return build_native_library("broker_core.cpp", _SO_NAME, force=force)


def _load():
    lib = ctypes.CDLL(build_library())
    lib.bc_create.restype = ctypes.c_void_p
    lib.bc_create.argtypes = [ctypes.c_uint32, ctypes.c_double]
    lib.bc_close.argtypes = [ctypes.c_void_p]
    lib.bc_destroy.argtypes = [ctypes.c_void_p]
    lib.bc_register_queue.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.bc_publish.restype = ctypes.c_uint64
    lib.bc_publish.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                               ctypes.c_char_p, ctypes.c_char_p,
                               ctypes.c_char_p,
                               ctypes.POINTER(ctypes.c_uint8),
                               ctypes.c_uint64]
    lib.bc_receive.restype = ctypes.POINTER(_MessageView)
    lib.bc_receive.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                               ctypes.c_int64]
    lib.bc_free_message.argtypes = [ctypes.POINTER(_MessageView)]
    lib.bc_complete.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                ctypes.c_uint64]
    lib.bc_abandon.restype = ctypes.c_int
    lib.bc_abandon.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                               ctypes.c_uint64]
    lib.bc_pop_dead_letter.restype = ctypes.POINTER(_MessageView)
    lib.bc_pop_dead_letter.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.bc_depth.restype = ctypes.c_uint64
    lib.bc_depth.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.bc_in_flight.restype = ctypes.c_uint64
    lib.bc_in_flight.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    return lib


_lib = None


def get_lib():
    global _lib
    if _lib is None:
        _lib = _load()
    return _lib


def _view_to_message(view) -> Message:
    v = view.contents
    body = bytes(ctypes.cast(
        v.body, ctypes.POINTER(ctypes.c_char * v.body_len)).contents) \
        if v.body_len else b""
    return Message(
        task_id=v.task_id.decode(),
        endpoint=v.endpoint.decode(),
        body=body,
        content_type=v.content_type.decode(),
        delivery_count=v.delivery_count,
        seq=v.seq,
    )


class NativeBroker:
    """InMemoryBroker-compatible facade over the C++ engine."""

    def __init__(self, max_delivery_count: int = 1440,
                 lease_seconds: float = 300.0, receive_threads: int = 8):
        self._lib = get_lib()
        self._handle = self._lib.bc_create(max_delivery_count,
                                           float(lease_seconds))
        self.max_delivery_count = max_delivery_count
        self.lease_seconds = lease_seconds
        self._registered: set[str] = set()
        self._dead_letter_handler: DeadLetterHandler | None = None
        self._loop = None
        # Blocking receives park here, off the event loop and off the GIL.
        self._executor = ThreadPoolExecutor(max_workers=receive_threads,
                                            thread_name_prefix="native-broker")

    # -- lifecycle ---------------------------------------------------------

    def bind_loop(self, loop=None) -> None:  # parity with InMemoryBroker
        self._loop = loop or asyncio.get_event_loop()

    def close(self) -> None:
        if not self._handle:
            return
        # Shutdown order matters: wake blocked receivers first (bc_close —
        # queues stay allocated), join the receive threads, then free the
        # engine. Destroying first would delete mutexes threads still wait on.
        self._lib.bc_close(self._handle)
        self._executor.shutdown(wait=True)
        self._lib.bc_destroy(self._handle)
        self._handle = None

    def _require_handle(self) -> None:
        if not self._handle:
            raise RuntimeError("NativeBroker is closed")

    def set_dead_letter_handler(self, handler: DeadLetterHandler | None) -> None:
        self._dead_letter_handler = handler

    def register_queue(self, name: str) -> None:
        self._registered.add(name)
        self._lib.bc_register_queue(self._handle, name.encode())

    def queue_names(self) -> list[str]:
        return sorted(self._registered)

    def depths(self) -> dict[str, int]:
        return {n: self._lib.bc_depth(self._handle, n.encode())
                for n in sorted(self._registered)}

    # -- publish -----------------------------------------------------------

    def publish(self, task) -> None:
        self._require_handle()
        body = task.body or b""
        buf = (ctypes.c_uint8 * len(body)).from_buffer_copy(body) if body \
            else (ctypes.c_uint8 * 0)()
        self._lib.bc_publish(
            self._handle,
            canonical_path(task.endpoint).encode(),
            task.task_id.encode(),
            task.endpoint.encode(),
            getattr(task, "content_type", "application/json").encode(),
            buf, len(body))

    # -- consume -----------------------------------------------------------

    def _receive_blocking(self, queue_name: str, timeout_ms: int) -> Message | None:
        if not self._handle:
            return None
        view = self._lib.bc_receive(self._handle, queue_name.encode(),
                                    timeout_ms)
        # Messages the C++ lease-reaper dead-lettered surface here — the
        # dispatcher's periodic receive doubles as the drain tick.
        self._drain_dead_letters(queue_name)
        if not view:
            return None
        try:
            msg = _view_to_message(view)
            msg.queue_name = queue_name
            return msg
        finally:
            self._lib.bc_free_message(view)

    async def receive(self, queue_name: str,
                      timeout: float | None = None) -> Message | None:
        timeout_ms = -1 if timeout is None else int(timeout * 1000)
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(
            self._executor, self._receive_blocking, queue_name, timeout_ms)

    def complete(self, msg: Message) -> None:
        self._lib.bc_complete(self._handle, msg.queue_name.encode(), msg.seq)

    def abandon(self, msg: Message) -> bool:
        rc = self._lib.bc_abandon(self._handle, msg.queue_name.encode(),
                                  msg.seq)
        if rc == 0:
            self._drain_dead_letters(msg.queue_name)
            return False
        return True

    def _drain_dead_letters(self, queue_name: str) -> None:
        if self._dead_letter_handler is None:
            return
        while True:
            view = self._lib.bc_pop_dead_letter(self._handle,
                                                queue_name.encode())
            if not view:
                return
            try:
                msg = _view_to_message(view)
                msg.queue_name = queue_name
            finally:
                self._lib.bc_free_message(view)
            handler = self._dead_letter_handler
            try:
                # May run on an executor thread; marshal onto the loop the
                # platform bound (its handler schedules coroutines).
                if self._loop is not None and not self._loop.is_closed():
                    self._loop.call_soon_threadsafe(handler, msg)
                else:
                    handler(msg)
            except Exception:  # noqa: BLE001
                log.exception("dead-letter handler failed for %s", msg.task_id)
