"""Per-backend completion estimator — the question every placement asks.

The resilience layer already knows whether a backend is *dead* (breaker
state) and the admission layer already knows how fast the platform
*drains*; neither can answer the per-request question orchestration
needs: **"what is the probability that THIS backend finishes THIS
request within its remaining deadline budget?"**

This module answers it from signals the platform already produces,
inventing none:

- **RTT samples** — the delivered-POST round trips the dispatcher's
  attempt loop (and the gateway sync proxy) already measure for the
  admission limiter are forked into one decayed quantile sketch per
  backend (``DecayedQuantiles``): the newest ``window`` samples, with
  anything older than ``horizon_s`` ignored, so a backend that was slow
  ten minutes ago is judged on what it does now;
- **breaker state** — an OPEN backend completes nothing (p = 0); a
  half-open backend is probation traffic, its estimate discounted;
- **queue pressure** — deliveries currently in flight against the
  backend stretch the expected completion time by ``p50 × inflight /
  parallelism`` before the empirical distribution is consulted.

The estimate is the *empirical* fraction of recent RTTs at or under the
effective budget — no distributional assumption, which matters because
serving RTTs are multi-modal (cache-warm vs compile-cold, small vs full
batches). A backend with no recent samples answers ``cold_p``
(optimistic by default): cold tiers must receive traffic to be learned,
and one observation is enough to start correcting.
"""

from __future__ import annotations

import time
from collections import deque
from urllib.parse import urlparse

from ..metrics import DEFAULT_REGISTRY, MetricsRegistry


def backend_label(uri: str) -> str:
    """Metrics label for a backend URI — the host, matching the
    ``backend`` dimension the dispatch and resilience families export."""
    return urlparse(uri).netloc or uri


class DecayedQuantiles:
    """Bounded, time-decayed RTT sample sketch.

    Holds the newest ``size`` ``(t, value)`` samples; queries ignore
    samples older than ``horizon_s``. O(size·log size) per query at the
    default size (256) is microseconds — far cheaper than maintaining a
    streaming quantile structure, and exact, which keeps the placement
    tests deterministic."""

    def __init__(self, size: int = 256, horizon_s: float = 60.0,
                 clock=time.monotonic):
        self.horizon_s = horizon_s
        self._clock = clock
        self._samples: deque[tuple[float, float]] = deque(maxlen=max(1, size))

    def observe(self, value: float, now: float | None = None) -> None:
        if value < 0:
            return
        now = self._clock() if now is None else now
        self._samples.append((now, value))

    def _live(self, now: float) -> list[float]:
        horizon = now - self.horizon_s
        return [v for t, v in self._samples if t >= horizon]

    def count(self, now: float | None = None) -> int:
        return len(self._live(self._clock() if now is None else now))

    def quantile(self, q: float, now: float | None = None) -> float | None:
        """The q-quantile of the live window, None when empty."""
        live = sorted(self._live(self._clock() if now is None else now))
        if not live:
            return None
        idx = min(len(live) - 1, max(0, int(q * len(live))))
        return live[idx]

    def p_le(self, threshold: float, now: float | None = None
             ) -> float | None:
        """Empirical P(sample <= threshold) over the live window, None
        when the window is empty (the caller decides the cold prior)."""
        live = self._live(self._clock() if now is None else now)
        if not live:
            return None
        return sum(1 for v in live if v <= threshold) / len(live)


class CompletionEstimator:
    """One quantile sketch per backend, crossed with the shared breaker
    state (``resilience.BackendHealth``) and the in-flight count the
    dispatcher reports around each delivery."""

    #: Half-open probation: the backend is being probed back to life —
    #: its history predates the outage, so trust it half as much.
    HALF_OPEN_DISCOUNT = 0.5

    def __init__(self, health, window: int = 256, horizon_s: float = 60.0,
                 cold_p: float = 1.0, parallelism: int = 8,
                 metrics: MetricsRegistry | None = None,
                 clock=time.monotonic):
        self.health = health
        self.window = window
        self.horizon_s = horizon_s
        self.cold_p = cold_p
        self.parallelism = max(1, parallelism)
        self.metrics = metrics or DEFAULT_REGISTRY
        self._clock = clock
        self._sketches: dict[str, DecayedQuantiles] = {}
        self._inflight: dict[str, int] = {}
        self._p50_gauge = self.metrics.gauge(
            "ai4e_orchestration_backend_p50_seconds",
            "Decayed median delivered-RTT per backend (the estimator's "
            "service-time anchor)")

    def _sketch(self, uri: str) -> DecayedQuantiles:
        sk = self._sketches.get(uri)
        if sk is None:
            sk = self._sketches[uri] = DecayedQuantiles(
                size=self.window, horizon_s=self.horizon_s,
                clock=self._clock)
        return sk

    # -- signal feeds -------------------------------------------------------

    def observe(self, uri: str, rtt_s: float, now: float | None = None
                ) -> None:
        """One *delivered* (2xx) round trip. Failures and backpressure
        answers never feed the sketch — an instantly-refusing backend
        must not look like the fastest tier."""
        sk = self._sketch(uri)
        sk.observe(rtt_s, now)
        p50 = sk.quantile(0.5, now)
        if p50 is not None:
            self._p50_gauge.set(p50, backend=backend_label(uri))

    def begin(self, uri: str) -> None:
        """A delivery against ``uri`` started (queue-pressure input)."""
        self._inflight[uri] = self._inflight.get(uri, 0) + 1

    def end(self, uri: str) -> None:
        self._inflight[uri] = max(0, self._inflight.get(uri, 0) - 1)

    def inflight(self, uri: str) -> int:
        return self._inflight.get(uri, 0)

    # -- the estimate -------------------------------------------------------

    def p_within(self, uri: str, budget_s: float,
                 now: float | None = None) -> float:
        """P(this backend completes a request placed now within
        ``budget_s``). Infinite budget → 1.0 for any non-open backend.

        The breaker crossing here (open → 0, half-open discounted) is a
        BACKSTOP for direct estimator consumers: ``Orchestrator.place``
        routes available-but-non-closed candidates through its probe
        step before this walk and excludes unavailable ones entirely, so
        on the placement path every backend evaluated here has a closed
        breaker — tune placement's treatment of recovering backends in
        ``place``, not via ``HALF_OPEN_DISCOUNT``."""
        now = self._clock() if now is None else now
        state = self.health.state(uri)
        if state == "open":
            return 0.0
        if budget_s == float("inf"):
            return 1.0
        sk = self._sketch(uri)
        p50 = sk.quantile(0.5, now)
        if p50 is None:
            p = self.cold_p
        else:
            # Queue-pressure discount: in-flight deliveries ahead of this
            # one consume budget before its own service time starts. The
            # backend serves ``parallelism`` of them concurrently (the
            # micro-batcher behind a worker makes true per-request
            # serialization rare), so the wait estimate is p50-per-wave.
            wait = (self._inflight.get(uri, 0) / self.parallelism) * p50
            p = sk.p_le(budget_s - wait, now)
            if p is None:
                p = self.cold_p
        if state == "half_open":
            p *= self.HALF_OPEN_DISCOUNT
        return p
