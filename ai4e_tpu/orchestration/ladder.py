"""Brownout / degradation ladder — declared modes instead of cliff-edge.

Without it, the platform's only answers to sustained predicted-miss
pressure are the shedder's per-class occupancy fractions (which react to
*backlog*, not to *prediction*) and the deadline-infeasibility shed.
Both are per-request; neither declares a platform STATE an operator can
see, alert on, or reason about. The ladder does: under sustained
predicted-miss pressure the platform steps through explicit modes, and
steps back down hysteretically once pressure clears.

Levels (``LEVELS``; each includes everything above it):

0. ``normal`` — nothing degraded.
1. ``reroute_background`` — background placements are restricted to the
   cheapest live backend tier (best-effort reroute; the orchestrator's
   ``place`` consults ``restrict_background``). Nothing is refused yet.
2. ``shed_background`` — background requests are refused at admission
   (429/503, ``X-Shed-Reason: brownout at <hop>``, drain-derived
   Retry-After).
3. ``shed_default`` — the default class is refused too; interactive
   traffic still serves, and because the gateway's cache consult runs
   BEFORE the brownout check, answers the result cache already holds
   keep flowing for every class (the cache-only degraded mode falls out
   of the existing request ordering — no special path).
4. ``shed_interactive`` — interactive is refused as well (503 with
   drain-derived Retry-After); cache hits remain the only service.

Pressure is the decayed fraction of *miss evidence* among deadline
events: predicted misses from placement (no backend cleared the
confidence bar) and actual misses from the store's terminal transitions
(``late`` completions, ``expired`` tasks), over all placements/outcomes
of deadline-carrying work. A ``min_rate`` guard keeps one early miss on
an idle platform from counting as 100% pressure, and makes an idle
platform step back down (no events → pressure reads 0).

Hysteresis: pressure must hold above ``up`` for ``hold_s`` before a
step up, below ``down`` for ``hold_s`` before a step down, one level
per hold — so a metrics blip can't slam the platform to
``shed_interactive`` and a single good second can't lift a brownout
that is about to re-form. ``up > down`` is required (the dead band IS
the hysteresis). Every transition is logged and counted
(``ai4e_orchestration_ladder_*``, docs/METRICS.md).

Thread-safety: ``note`` arrives from the event loop (placements) and
from whatever thread runs the store upsert (terminal transitions) —
level transitions run under one lock. ``level`` and
``restrict_background`` are lock-free int reads; ``refuse`` TAKES the
lock (its consult-time ``evaluate`` is what unwedges a full brownout),
so never call it while holding a lock ordered after this one.
"""

from __future__ import annotations

import logging
import threading
import time

from ..admission.controller import DecayingRate
from ..admission.deadline import BACKGROUND, DEFAULT, priority_name
from ..metrics import DEFAULT_REGISTRY, MetricsRegistry

log = logging.getLogger("ai4e_tpu.orchestration")

LEVELS = ("normal", "reroute_background", "shed_background",
          "shed_default", "shed_interactive")


class DegradationLadder:
    def __init__(self, up: float = 0.3, down: float = 0.1,
                 hold_s: float = 5.0, min_rate: float = 1.0,
                 tau_s: float = 10.0,
                 metrics: MetricsRegistry | None = None,
                 clock=time.monotonic):
        if not (0.0 <= down < up <= 1.0):
            raise ValueError(
                f"ladder thresholds need 0 <= down < up <= 1, got "
                f"down={down} up={up}")
        self.up = up
        self.down = down
        self.hold_s = hold_s
        self.min_rate = min_rate
        self.metrics = metrics or DEFAULT_REGISTRY
        self._clock = clock
        self._miss = DecayingRate(tau_s=tau_s)
        self._total = DecayingRate(tau_s=tau_s)
        self.level = 0
        self._lock = threading.Lock()
        self._above_since: float | None = None
        self._below_since: float | None = None
        self._level_gauge = self.metrics.gauge(
            "ai4e_orchestration_ladder_level",
            "Degradation-ladder level: 0 normal .. 4 shed_interactive")
        self._level_gauge.set(0)
        self._transitions = self.metrics.counter(
            "ai4e_orchestration_ladder_transitions_total",
            "Ladder steps by direction and the mode entered")
        self._refusals = self.metrics.counter(
            "ai4e_orchestration_brownout_refusals_total",
            "Admissions refused by the ladder, by priority and mode")

    @property
    def mode(self) -> str:
        return LEVELS[self.level]

    # -- pressure feed ------------------------------------------------------

    def note(self, miss: bool, now: float | None = None,
             n: float = 1.0) -> None:
        """``n`` units of deadline evidence: a placement decision (miss =
        nobody cleared the confidence bar), a terminal outcome (miss =
        late/expired), or — batched via ``n`` — an SLO engine tick's
        worth of requests (one note per multi-second tick would decay
        below the ``min_rate`` evidence floor and never move the
        ladder; the engine passes the window's event count instead).
        Evaluates transitions inline — the ladder needs no background
        task."""
        now = self._clock() if now is None else now
        self._total.on_event(n, now=now)
        if miss:
            self._miss.on_event(n, now=now)
        self.evaluate(now)

    def pressure(self, now: float | None = None) -> float:
        now = self._clock() if now is None else now
        total = self._total.rate(now)
        if total < self.min_rate:
            # Too little deadline traffic to judge — and the decay of an
            # idle platform's rates lands here, which is what steps a
            # stale brownout back down.
            return 0.0
        return min(1.0, self._miss.rate(now) / total)

    # -- transitions --------------------------------------------------------

    def evaluate(self, now: float | None = None) -> int:
        """Apply the hysteresis rule; returns the (possibly new) level."""
        now = self._clock() if now is None else now
        p = self.pressure(now)
        with self._lock:
            if p >= self.up and self.level < len(LEVELS) - 1:
                self._below_since = None
                if self._above_since is None:
                    self._above_since = now
                elif now - self._above_since >= self.hold_s:
                    self._step(+1, p, now)
                    # Re-arm: the NEXT step up needs a fresh hold window.
                    self._above_since = now
            elif p <= self.down and self.level > 0:
                self._above_since = None
                if self._below_since is None:
                    self._below_since = now
                elif now - self._below_since >= self.hold_s:
                    self._step(-1, p, now)
                    self._below_since = now
            else:
                # Dead band (or already at an end stop): both hold timers
                # reset — a step requires SUSTAINED evidence, not
                # accumulated flickers.
                self._above_since = None
                self._below_since = None
            return self.level

    def _step(self, direction: int, pressure: float, now: float) -> None:
        self.level += direction
        mode = LEVELS[self.level]
        self._level_gauge.set(self.level)
        self._transitions.inc(direction="up" if direction > 0 else "down",
                              mode=mode)
        log.warning("degradation ladder %s -> %s (predicted-miss pressure "
                    "%.2f)", LEVELS[self.level - direction], mode, pressure)

    # -- policy queries -----------------------------------------------------

    def restrict_background(self) -> bool:
        """Level >= 1: background placements go to the cheapest live
        tier only (best-effort reroute ahead of any shedding)."""
        return self.level >= 1

    def refuse(self, priority: int) -> str | None:
        """The mode name when the ladder refuses this class right now,
        else None. Counting happens here because every non-None answer
        IS a refusal at the calling hop (admission 429/503).

        Transitions are re-evaluated FIRST: at ``shed_interactive``
        every admission is refused, so no placements and (once the
        backlog drains) no terminal outcomes ever call ``note`` again —
        without this consult-time evaluate, the ladder would wedge at
        full brownout forever even after pressure decayed to nothing.
        Clients keep knocking (they were told Retry-After), and each
        knock is the clock tick that steps a stale brownout down."""
        self.evaluate()
        level = self.level
        refused = (level >= 4
                   or (level >= 3 and priority >= DEFAULT)
                   or (level >= 2 and priority >= BACKGROUND))
        if not refused:
            return None
        mode = LEVELS[level]
        self._refusals.inc(priority=priority_name(priority), mode=mode)
        return mode
