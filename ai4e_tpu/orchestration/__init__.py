"""Deadline-aware orchestration over unequal backends (docs/orchestration.md).

Three pieces, all composing signals the platform already produces:

- ``CompletionEstimator`` (estimator.py) — per-backend decayed RTT
  quantile sketches crossed with breaker state and queue pressure,
  answering P(finishes within the remaining deadline budget);
- ``DegradationLadder`` (ladder.py) — declared brownout modes stepped
  through hysteretically under sustained predicted-miss pressure,
  consulted by the admission shedder;
- ``Orchestrator`` (core.py) — the cheapest-backend-that-clears-the-bar
  placement replacing the health-weighted random pick in the dispatcher
  and the gateway sync proxy.

Opt-in via ``PlatformConfig(orchestration=True)`` /
``AI4E_PLATFORM_ORCHESTRATION=1`` (requires admission + resilience —
the layers whose signals it composes); off, the assembly is byte-
identical to pre-orchestration behavior.
"""

from .core import Orchestrator, OrchestrationPolicy, parse_costs
from .estimator import CompletionEstimator, DecayedQuantiles, backend_label
from .ladder import LEVELS, DegradationLadder

__all__ = [
    "Orchestrator",
    "OrchestrationPolicy",
    "parse_costs",
    "CompletionEstimator",
    "DecayedQuantiles",
    "backend_label",
    "DegradationLadder",
    "LEVELS",
]
