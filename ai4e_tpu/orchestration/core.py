"""The orchestrator — cost- and deadline-aware placement over unequal
backends, replacing the dispatcher/proxy's health-weighted random pick.

The resilience layer treats all backends of a route as interchangeable:
a weighted random pick over whoever's breaker admits traffic. That is
the right default for a homogeneous canary pair and the wrong one for a
mixed fleet (TPU-class, CPU fallback, remote HTTP) where tiers differ by
orders of magnitude in both latency and cost (PAPERS 2503.20074,
2602.04900). ``place`` chooses per request:

1. candidates = the route's backends whose breaker admits traffic (and
   not already tried in this delivery's failover chain);
2. under brownout level >= 1, background work is restricted to the
   cheapest live tier (``ladder.restrict_background`` — best-effort
   reroute ahead of any shedding);
3. a PROBE-ELIGIBLE candidate — breaker non-closed but admitting
   traffic (cooldown elapsed, probe slot free) — takes the request
   outright (``probe``): under the resilience pick a recovering backend
   competes at its normal weight, but a p-based walk would starve it
   forever (an open breaker's estimate is 0, so a healthy cheaper peer
   always wins and the probe that would close the breaker never fires —
   a live-drive caught exactly this). The breaker's own probe-slot
   accounting bounds the diversion to ``half_open_probes`` in-flight
   requests, and a failed probe re-opens the cooldown;
4. otherwise walk cost TIERS cheapest-first (cost from the policy's
   substring map) and take the first tier with a candidate whose
   ``p_within(remaining deadline budget)`` clears the confidence bar —
   the cheapest tier predicted to make the deadline, which is the whole
   game. WITHIN the tier, the choice is a weighted pick over everybody
   who cleared: equal-cost backends are a canary split, and a
   deterministic first-clears-wins walk would starve the minority
   backend of the traffic its error-rate series exists to measure;
5. nobody clears → the candidate with the best p serves anyway
   (``fallback``) and the ladder is fed one predicted-miss unit — this
   is the pressure signal brownouts are built from;
6. nothing available at all (every breaker open / everything excluded)
   → delegate to the health model's forced-probe pick (``forced``), the
   dark-set self-healing PR 3 established.

Requests WITHOUT a deadline have an infinite budget: every live backend
clears, so they simply take the cheapest tier — exactly the cost-aware
behavior batch traffic wants, with zero configuration.

A chosen non-closed backend is committed through the health model
(``commit_pick``) so half-open probe accounting is identical whether the
resilience pick or the orchestrator chose it.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

from ..admission.deadline import BACKGROUND, remaining_s
from ..metrics import DEFAULT_REGISTRY, MetricsRegistry
from ..utils.backends import pick_backend
from .estimator import CompletionEstimator, backend_label
from .ladder import DegradationLadder


@dataclass
class OrchestrationPolicy:
    """Assembly-level knob set (``PlatformConfig.orchestration_*`` /
    ``AI4E_PLATFORM_ORCHESTRATION*`` mirror the env-visible ones)."""

    confidence: float = 0.75      # p_within bar a backend must clear
    window: int = 256             # RTT samples per backend sketch
    horizon_s: float = 60.0       # sample age beyond which RTTs are ignored
    cold_p: float = 1.0           # estimate for a backend with no samples
    backend_parallelism: int = 8  # assumed concurrent service per backend
    # Cost per backend: substring → relative cost (first match wins, like
    # the fault injector's rules); unmatched backends cost 1.0. Lower is
    # cheaper; ties preserve configured weight order.
    costs: dict = field(default_factory=dict)
    # Ladder thresholds (ladder.py): predicted-miss pressure to step
    # up/down, and the sustain window per step.
    ladder_up: float = 0.3
    ladder_down: float = 0.1
    ladder_hold_s: float = 5.0
    # Predictive autoscaling projection window (scaling/autoscaler.py):
    # how far ahead the arrival/drain imbalance is integrated.
    scale_horizon_s: float = 10.0


def parse_costs(spec: str | None) -> dict:
    """``"tpu=3,cpu-fallback=1,remote=5"`` → substring→cost map (the
    config-string form of ``OrchestrationPolicy.costs``)."""
    costs: dict[str, float] = {}
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        name, sep, raw = part.partition("=")
        if not sep:
            raise ValueError(
                f"orchestration cost entry {part!r} is not substring=cost")
        costs[name.strip()] = float(raw)
    return costs


class Orchestrator:
    """One per assembly: estimator + ladder + the placement policy, shared
    by every dispatcher and the gateway sync proxy the same way the
    health model is."""

    def __init__(self, health, policy: OrchestrationPolicy | None = None,
                 metrics: MetricsRegistry | None = None,
                 clock=time.monotonic):
        self.health = health
        self.policy = policy or OrchestrationPolicy()
        self.metrics = metrics or DEFAULT_REGISTRY
        self._clock = clock
        self.estimator = CompletionEstimator(
            health, window=self.policy.window,
            horizon_s=self.policy.horizon_s, cold_p=self.policy.cold_p,
            parallelism=self.policy.backend_parallelism,
            metrics=self.metrics, clock=clock)
        self.ladder = DegradationLadder(
            up=self.policy.ladder_up, down=self.policy.ladder_down,
            hold_s=self.policy.ladder_hold_s, metrics=self.metrics,
            clock=clock)
        self._placements = self.metrics.counter(
            "ai4e_orchestration_placements_total",
            "Placement decisions by backend and outcome (confident/"
            "fallback/probe/forced)")

    # -- signal feeds (the dispatcher/proxy call these) ---------------------

    def observe(self, uri: str, rtt_s: float) -> None:
        self.estimator.observe(uri, rtt_s)

    def begin(self, uri: str) -> None:
        self.estimator.begin(uri)

    def end(self, uri: str) -> None:
        self.estimator.end(uri)

    # -- cost model ---------------------------------------------------------

    def cost_of(self, uri: str) -> float:
        for sub, cost in self.policy.costs.items():
            if sub in uri:
                return cost
        return 1.0

    # -- placement ----------------------------------------------------------

    def place(self, backends, deadline_at: float = 0.0, priority: int = 1,
              rng=None, exclude=(), note=None) -> str:
        """Choose the delivery target for one request (module docstring).
        ``backends``/``exclude`` carry the same contract as
        ``BackendHealth.pick`` — weighted set, failover exclusion ignored
        when it would empty the set. ``note`` (optional,
        ``note(outcome, uri)``) receives the placement outcome label AND
        the chosen backend — the observability layer stamps both onto
        the task's hop ledger (``placed``/``probe`` events; a probe
        event without the probed backend would carry no diagnostic
        value) without changing the return contract either call site
        depends on."""
        now = self._clock()

        def _tell(outcome: str, uri: str) -> None:
            if note is not None:
                try:
                    note(outcome, uri)
                except Exception:  # noqa: BLE001; ai4e: noqa[AIL005] — an observability sink must never fail a placement
                    pass
        pool = [(u, w) for u, w in backends if u not in exclude and w > 0]
        if not pool:
            pool = [(u, w) for u, w in backends if w > 0]
        # Drain eject (rollout/): route around a draining backend while
        # any peer remains — an eject-from-placement, not a breaker
        # event, so a planned upgrade never reads as a failure.
        undrained = [(u, w) for u, w in pool
                     if not self.health.is_draining(u)]
        if undrained:
            pool = undrained
        # Canary split (rollout/canary.py): rescale so the canary
        # generation's backends hold their configured traffic share; the
        # rescaled weights carry through the in-tier weighted pick below.
        if self.health.canary is not None:
            pool = [(u, w) for u, w in self.health.canary.apply(pool)
                    if w > 0] or pool
        avail = [(u, w) for u, w in pool
                 if self.health.breaker_for(u).available(now)]
        if not avail:
            # Fully dark (or fully excluded): the health model's forced
            # probe of the least-recently-failed backend — a dark set
            # must keep probing its way back to life.
            chosen = self.health.pick(backends, rng, exclude=exclude)
            self._placements.inc(backend=backend_label(chosen),
                                 outcome="forced")
            _tell("forced", chosen)
            return chosen
        if priority >= BACKGROUND and self.ladder.restrict_background():
            cheapest = min(self.cost_of(u) for u, _ in avail)
            avail = [(u, w) for u, w in avail
                     if self.cost_of(u) <= cheapest]
        # Cheapest-first; heavier configured weight breaks cost ties so a
        # weighted canary pair still skews toward its majority backend.
        order = sorted(range(len(avail)),
                       key=lambda i: (self.cost_of(avail[i][0]),
                                      -avail[i][1], i))
        # Recovery probe (docstring step 3): an available-but-non-closed
        # backend would never win the p walk (its estimate is 0/discounted
        # while any healthy peer clears), so route this request to it as
        # the probe that can close its breaker. Self-limiting: the slot
        # this commit_pick books makes the backend unavailable to the
        # next placement until the probe resolves. No ladder note — a
        # probe is not a prediction.
        for i in order:
            uri = avail[i][0]
            if self.health.state(uri) != "closed":
                self.health.commit_pick(uri, now)
                self._placements.inc(backend=backend_label(uri),
                                     outcome="probe")
                _tell("probe", uri)
                return uri
        budget = remaining_s(deadline_at)
        chosen = None
        outcome = "confident"
        best, best_p = avail[order[0]][0], -1.0
        tier_start = 0
        while tier_start < len(order):
            tier_cost = self.cost_of(avail[order[tier_start]][0])
            tier_end = tier_start
            while (tier_end < len(order)
                   and self.cost_of(avail[order[tier_end]][0]) == tier_cost):
                tier_end += 1
            clearing = []
            for i in order[tier_start:tier_end]:
                uri, weight = avail[i]
                p = self.estimator.p_within(uri, budget, now)
                if p > best_p:
                    best, best_p = uri, p
                if p >= self.policy.confidence:
                    clearing.append((uri, weight))
            if clearing:
                # Weighted pick over the tier's clearing members — an
                # equal-cost set keeps its configured canary split.
                chosen = pick_backend(clearing, rng)
                break
            tier_start = tier_end
        if chosen is None:
            # Nobody clears the bar: serve best-effort on the highest-p
            # tier and feed the ladder the predicted miss (only deadline
            # traffic can miss).
            chosen, outcome = best, "fallback"
            if budget != float("inf"):
                self.ladder.note(miss=True, now=now)
        elif budget != float("inf"):
            self.ladder.note(miss=False, now=now)
        self.health.commit_pick(chosen, now)
        self._placements.inc(backend=backend_label(chosen), outcome=outcome)
        _tell(outcome, chosen)
        return chosen
