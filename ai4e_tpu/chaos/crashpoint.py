"""Crash-point sweep — kill/restart a journaled store at EVERY record
boundary and at seeded mid-record offsets, and prove the reboot contract
at each one.

The r5 HA drive proved "0 lost across a SIGKILL"; this harness proves
the layer *below* it: whatever byte the journal happens to end at — a
clean record boundary (process kill between appends), a torn mid-record
offset (kill mid-write), or a lost page-cache tail (machine crash under
``fsync=never``, emulated by ``disk.lose_page_cache``-style prefix
truncation) — the restarted store must

1. **boot** (no crash-loop: boot-salvage truncates the torn tail);
2. hold **every acknowledged mutation whose ack marker fits the
   surviving prefix** — under ``fsync=always`` the marker is durable at
   ack time, so this is the literal "0 acknowledged-task loss" claim;
   under ``fsync=never``/``group`` the same sweep documents the residual
   window honestly (the check is byte-conditional, not policy-
   conditional: state must equal the surviving prefix's history);
3. show **no duplicate or conflicting state** — each task in exactly one
   status set, status equal to its last surviving transition, never a
   terminal status it reached only after the crash point;
4. **converge a replica**: a fresh follower absorbing the rebooted
   journal ends chain-head-identical to the primary with an identical
   task snapshot.

Driven across seeds by ``tests/test_disk_chaos.py`` and the CI
``durability-smoke`` job (fixed-seed subset).
"""

from __future__ import annotations

import os
import random
from dataclasses import dataclass, field

from ..taskstore import TaskStatus
from ..taskstore.journal import JournalCorruptError
from ..taskstore.store import FollowerTaskStore, JournaledTaskStore
from ..taskstore.task import APITask


@dataclass
class AckEvent:
    """One acknowledged mutation: the journal byte size the moment the
    store returned success (= the prefix that must preserve it)."""
    marker: int
    kind: str                 # create | transition | result | evict
    status: str | None = None
    result: bytes | None = None


@dataclass
class WorkloadTrace:
    """Everything the reboot check needs about the driven run."""
    journal_path: str
    fsync: str
    seed: int
    journal_bytes: bytes = b""
    # task_id -> ordered AckEvents (markers strictly increase).
    events: dict[str, list[AckEvent]] = field(default_factory=dict)

    def expectation_at(self, task_id: str, crash_at: int
                       ) -> AckEvent | None:
        """The last acknowledged event whose bytes fit the surviving
        prefix — what the rebooted store must show."""
        last = None
        for ev in self.events[task_id]:
            if ev.marker <= crash_at:
                last = ev
        return last


def drive_workload(journal_path: str, seed: int, fsync: str = "always",
                   ops: int = 40) -> WorkloadTrace:
    """Run a seeded mutation mix (creates, completions, failures, result
    writes, evictions) against a fresh journaled store, recording each
    ack beside the journal size at that instant. Every append is flushed
    before the caller unblocks, so the file size IS the ack marker."""
    from ..metrics import MetricsRegistry
    rng = random.Random(seed)
    trace = WorkloadTrace(journal_path=journal_path, fsync=fsync, seed=seed)
    store = JournaledTaskStore(journal_path, fsync=fsync,
                               metrics=MetricsRegistry())
    live: list[str] = []

    def marker() -> int:
        return store._stat_bytes

    for i in range(ops):
        choice = rng.random()
        if choice < 0.45 or not live:
            body = rng.randbytes(rng.randrange(4, 64))
            task = store.upsert(APITask(endpoint="/v1/sweep/x", body=body,
                                        status="created", publish=False))
            trace.events[task.task_id] = [
                AckEvent(marker(), "create", "created")]
            live.append(task.task_id)
        elif choice < 0.75:
            tid = rng.choice(live)
            terminal = rng.random() < 0.7
            status = (TaskStatus.COMPLETED if terminal and rng.random() < 0.8
                      else TaskStatus.FAILED if terminal
                      else TaskStatus.RUNNING)
            store.update_status(tid, f"{status} - sweep op {i}", status)
            trace.events[tid].append(AckEvent(
                marker(), "transition", status))
            if terminal:
                live.remove(tid)
        elif choice < 0.9:
            tid = rng.choice(live)
            payload = rng.randbytes(rng.randrange(8, 48))
            store.set_result(tid, payload)
            trace.events[tid].append(AckEvent(
                marker(), "result", None, payload))
        else:
            # Evict everything terminal right now (retention with age 0):
            # the journal gains Evict records; a prefix that holds one
            # must show the task GONE, a prefix that cuts it must not.
            evicted = [t for t, evs in trace.events.items()
                       if evs[-1].status in TaskStatus.TERMINAL
                       and evs[-1].kind != "evict"]
            store.evict_terminal_older_than(0.0)
            for tid in evicted:
                trace.events[tid].append(AckEvent(marker(), "evict"))
    store.close()
    with open(journal_path, "rb") as fh:
        trace.journal_bytes = fh.read()
    _rebase_evict_markers(trace)
    return trace


def _rebase_evict_markers(trace: WorkloadTrace) -> None:
    """A batch eviction appends one Evict record PER victim inside one
    store-lock hold; the driver only observes the journal size after the
    whole batch. Rebase each task's evict marker onto its own record's
    end offset — a crash landing between two of the batch's appends must
    expect exactly the evictions whose records fit the prefix."""
    from ..taskstore.journal import verify_line
    data = trace.journal_bytes
    offset = 0
    while offset < len(data):
        nl = data.find(b"\n", offset)
        if nl == -1:
            break
        line = data[offset:nl].decode("utf-8").strip()
        end = nl + 1
        if line:
            rec, _chain, _legacy = verify_line(line, None)
            if rec.get("Evict"):
                for ev in trace.events.get(rec.get("TaskId", ""), ()):
                    if ev.kind == "evict":
                        ev.marker = end
        offset = end


def crash_offsets(trace: WorkloadTrace, rng: random.Random,
                  mid_points: int = 12) -> list[int]:
    """Every record boundary (kill between appends) plus ``mid_points``
    seeded strictly-mid-record offsets (kill mid-write / short write) —
    including offset 0 (crash before the first byte) and EOF (clean)."""
    data = trace.journal_bytes
    boundaries = [0]
    at = 0
    while True:
        nl = data.find(b"\n", at)
        if nl == -1:
            break
        boundaries.append(nl + 1)
        at = nl + 1
    mids = set()
    lines = [(boundaries[i], boundaries[i + 1])
             for i in range(len(boundaries) - 1)
             if boundaries[i + 1] - boundaries[i] > 2]
    for _ in range(mid_points):
        if not lines:
            break
        start, end = rng.choice(lines)
        mids.add(rng.randrange(start + 1, end - 1))
    return sorted(set(boundaries) | mids)


def check_reboot(trace: WorkloadTrace, crash_at: int, scratch_path: str
                 ) -> list[str]:
    """Crash the journaled store at byte ``crash_at`` (prefix truncation —
    the superset model covering kill-mid-write AND lost page cache) and
    verify the reboot contract. Returns human-readable violations."""
    from ..metrics import MetricsRegistry
    violations: list[str] = []
    with open(scratch_path, "wb") as fh:
        fh.write(trace.journal_bytes[:crash_at])
    try:
        store = JournaledTaskStore(scratch_path, metrics=MetricsRegistry())
    except JournalCorruptError as exc:
        return [f"crash@{crash_at}: reboot REFUSED a prefix-truncated "
                f"journal (must salvage, not quarantine): {exc}"]
    except Exception as exc:  # noqa: BLE001; ai4e: noqa[AIL005] — the exception IS the finding: it returns as a sweep violation
        return [f"crash@{crash_at}: reboot crash-looped: {exc!r}"]
    try:
        for tid in trace.events:
            expect = trace.expectation_at(tid, crash_at)
            try:
                record = store.get(tid)
            except Exception:  # noqa: BLE001; ai4e: noqa[AIL005] — absence is the probed signal; a miss feeds the ACKED-TASK-LOST check below
                record = None
            if expect is None or expect.kind == "evict":
                # Nothing acknowledged inside the prefix (or an
                # acknowledged eviction): the id must be absent — a
                # present record would be state from BEYOND the crash
                # point or a resurrected eviction.
                if record is not None and expect is not None:
                    violations.append(
                        f"crash@{crash_at}: task {tid} evicted at "
                        f"{expect.marker} but resurrected after reboot")
                continue
            if record is None:
                violations.append(
                    f"crash@{crash_at}: ACKED TASK LOST — {tid} "
                    f"acknowledged at journal byte {expect.marker} "
                    f"<= crash point, absent after reboot")
                continue
            want = _last_status_at(trace, tid, crash_at)
            if want is not None and record.canonical_status != want:
                violations.append(
                    f"crash@{crash_at}: task {tid} status "
                    f"{record.canonical_status!r} != last acknowledged "
                    f"{want!r}")
            want_result = _last_result_at(trace, tid, crash_at)
            if want_result is not None:
                found = store.get_result(tid)
                if found is None or found[0] != want_result:
                    violations.append(
                        f"crash@{crash_at}: task {tid} acknowledged "
                        "result missing or altered after reboot")
        violations.extend(_set_consistency(store, crash_at))
        violations.extend(_replica_convergence(store, scratch_path,
                                               crash_at))
    finally:
        store.close()
    return violations


def _last_status_at(trace: WorkloadTrace, tid: str,
                    crash_at: int) -> str | None:
    last = None
    for ev in trace.events[tid]:
        if ev.marker <= crash_at and ev.status is not None:
            last = ev.status
    return last


def _last_result_at(trace: WorkloadTrace, tid: str,
                    crash_at: int) -> bytes | None:
    last = None
    for ev in trace.events[tid]:
        if ev.marker <= crash_at and ev.kind == "result":
            last = ev.result
    return last


def _set_consistency(store: JournaledTaskStore, crash_at: int) -> list[str]:
    """Each task in exactly ONE status set, and that set matching its
    record — the structural "no duplicate/conflicting completion" check
    (a task in two sets is the replay-side shape of a double terminal)."""
    out = []
    memberships: dict[str, list[str]] = {}
    for (path, status), members in store._sets.items():
        for tid in members:
            memberships.setdefault(tid, []).append(status)
    for tid, record in store._tasks.items():
        sets = memberships.get(tid, [])
        if len(sets) != 1 or sets[0] != record.canonical_status:
            out.append(f"crash@{crash_at}: task {tid} status-set "
                       f"memberships {sets} vs record "
                       f"{record.canonical_status!r}")
    for tid in memberships:
        if tid not in store._tasks:
            out.append(f"crash@{crash_at}: orphan status-set entry {tid}")
    return out


def _replica_convergence(store: JournaledTaskStore, journal_path: str,
                         crash_at: int) -> list[str]:
    """A fresh follower absorbing the rebooted journal must end chain-
    head-identical with an identical task snapshot — the per-shard
    convergence claim, provable store-by-store."""
    from ..metrics import MetricsRegistry
    out = []
    replica_path = journal_path + ".replica-check"
    replica = FollowerTaskStore(replica_path, metrics=MetricsRegistry())
    try:
        replica.reset()
        with open(journal_path, encoding="utf-8") as fh:
            lines = [ln.rstrip("\n") for ln in fh if ln.strip()]
        try:
            replica.absorb_lines(lines)
        except JournalCorruptError as exc:
            return [f"crash@{crash_at}: replica refused the REBOOTED "
                    f"(salvaged) journal: {exc}"]
        if replica.replica_chain_head != store.chain_head:
            out.append(
                f"crash@{crash_at}: replica chain head "
                f"{replica.replica_chain_head} != primary "
                f"{store.chain_head}")
        mine = {t.task_id: t.canonical_status for t in store.snapshot()}
        theirs = {t.task_id: t.canonical_status
                  for t in replica.snapshot()}
        if mine != theirs:
            out.append(f"crash@{crash_at}: replica snapshot diverges "
                       f"({len(mine)} vs {len(theirs)} tasks or "
                       "differing statuses)")
    finally:
        replica.close()
        for suffix in ("", ".salvage.json"):
            try:
                os.unlink(replica_path + suffix)
            except OSError:
                pass
    return out


def sweep(workdir: str, seed: int, fsync: str = "always", ops: int = 40,
          mid_points: int = 12) -> tuple[int, list[str]]:
    """Full sweep for one seed: drive the workload, then crash/reboot at
    every boundary + seeded mid-record offsets. Returns
    ``(crash_points_checked, violations)`` — green is ``(N, [])``."""
    rng = random.Random(seed ^ 0x5EED)
    journal = os.path.join(workdir, f"sweep-{seed}.journal")
    trace = drive_workload(journal, seed, fsync=fsync, ops=ops)
    offsets = crash_offsets(trace, rng, mid_points=mid_points)
    violations: list[str] = []
    scratch = os.path.join(workdir, f"sweep-{seed}.crash")
    for crash_at in offsets:
        point = check_reboot(trace, crash_at, scratch)
        if point:
            _dump_sweep_artifacts(trace, crash_at, scratch, point)
        violations.extend(point)
        for suffix in ("", ".salvage.json"):
            try:
                os.unlink(scratch + suffix)
            except OSError:
                pass
    return len(offsets), violations


def _dump_sweep_artifacts(trace: WorkloadTrace, crash_at: int,
                          scratch: str, violations: list[str]) -> None:
    """Ship a red crash point's evidence (AI4E_CHAOS_DUMP_DIR, the same
    directory CI's durability-smoke job uploads on failure): the exact
    crashed journal prefix, the boot-salvage report it produced, and the
    violation list — a red sweep is debuggable without a local repro."""
    import json
    import shutil
    directory = (os.environ.get("AI4E_CHAOS_DUMP_DIR") or "/tmp/ai4e-chaos")
    try:
        os.makedirs(directory, exist_ok=True)
        tag = f"sweep-seed{trace.seed}-{trace.fsync.replace(':', '_')}-at{crash_at}"
        with open(os.path.join(directory, tag + ".violations.json"), "w",
                  encoding="utf-8") as fh:
            json.dump({"seed": trace.seed, "fsync": trace.fsync,
                       "crash_at": crash_at,
                       "violations": violations}, fh, indent=1)
        for src, suffix in ((scratch, ".journal"),
                            (scratch + ".salvage.json", ".salvage.json")):
            if os.path.exists(src):
                shutil.copyfile(src, os.path.join(directory, tag + suffix))
    except OSError:
        import logging
        logging.getLogger("ai4e_tpu.chaos").exception(
            "could not write crash-point sweep artifacts to %s", directory)
