"""Deterministic, seeded fault injection for the platform's two transport
surfaces: the HTTP hop (dispatcher delivery POSTs, gateway sync proxy)
and the queue publish surface.

The injector never monkeypatches aiohttp internals — it wraps the
``SessionHolder`` each component already owns, so the production code
path is byte-identical when no injector is installed and the faults a
test sees are exactly the faults the component's own error handling must
survive:

- ``error``          — the backend "answers" the injected status; the
  real request is **not** sent (the backend never executed);
- ``connect_error``  — ``aiohttp.ClientConnectionError`` before any
  bytes move (crashed pod / refused connection);
- ``drop``           — the real request IS sent and the backend executes,
  but the response is lost (``asyncio.TimeoutError``) — the
  at-least-once hazard: the sender must redeliver work that may already
  have completed;
- ``latency``        — an added sleep before the hop proceeds (composable
  with success or any fault above);
- ``duplicate``      — queue surface: the publish fires twice, minting
  two broker messages for one task (the lease-expiry redelivery hazard,
  injected on demand).

One seeded ``random.Random`` drives every draw, so a scenario replays
identically under a fixed seed and call order. Rules match backends by
URL substring (``"*"`` = every hop) and can be bounded (``times=N``) to
schedule "exactly one outage" style faults.
"""

from __future__ import annotations

import asyncio
import random
from collections import Counter
from dataclasses import dataclass, field

import aiohttp


@dataclass
class FaultRule:
    backend: str = "*"            # substring match on the target URL
    error_rate: float = 0.0
    error_status: int = 500
    connect_error_rate: float = 0.0
    drop_rate: float = 0.0
    latency_rate: float = 0.0
    latency_s: float = 0.0
    duplicate_rate: float = 0.0   # queue surface (wrap_publish)
    times: int | None = None      # max faults this rule injects; None = ∞
    _injected: int = field(default=0, repr=False)

    def matches(self, url: str) -> bool:
        return self.backend == "*" or self.backend in url

    def exhausted(self) -> bool:
        return self.times is not None and self._injected >= self.times


@dataclass
class Decision:
    fault: str | None = None      # "error" | "connect_error" | "drop" | None
    status: int = 500
    latency_s: float = 0.0


class FaultInjector:
    """Seeded fault source shared by every wrapped surface."""

    def __init__(self, seed: int = 0):
        self.seed = seed
        self.rng = random.Random(seed)
        self.rules: list[FaultRule] = []
        self.injected: Counter = Counter()

    def add_rule(self, backend: str = "*", **spec) -> FaultRule:
        rule = FaultRule(backend=backend, **spec)
        self.rules.append(rule)
        return rule

    def blackout(self, backend: str) -> FaultRule:
        """Total darkness for matching backends — every hop refuses the
        connection until ``lift``. Inserted at the FRONT of the rule list
        so an existing background-noise rule can't shadow it (``decide``
        takes the first matching rule). The dark-fleet scenario lever:
        30% of a tier dark is ``blackout`` on 1 of its 3 backends."""
        rule = FaultRule(backend=backend, connect_error_rate=1.0)
        self.rules.insert(0, rule)
        return rule

    def lift(self, rule: FaultRule) -> None:
        """End a ``blackout`` (idempotent)."""
        if rule in self.rules:
            self.rules.remove(rule)

    def counts(self) -> dict:
        return dict(self.injected)

    def _rule_for(self, url: str) -> FaultRule | None:
        for rule in self.rules:
            if rule.matches(url) and not rule.exhausted():
                return rule
        return None

    def decide(self, url: str) -> Decision:
        """One HTTP-hop draw. Faults are mutually exclusive (stacked
        probability bands over a single uniform draw); latency is an
        independent draw so a slow backend can also fail."""
        rule = self._rule_for(url)
        if rule is None:
            return Decision()
        d = Decision(status=rule.error_status)
        if rule.latency_rate > 0 and self.rng.random() < rule.latency_rate:
            d.latency_s = rule.latency_s
            self.injected["latency"] += 1
        r = self.rng.random()
        edge = rule.connect_error_rate
        if r < edge:
            d.fault = "connect_error"
        elif r < (edge := edge + rule.drop_rate):
            d.fault = "drop"
        elif r < edge + rule.error_rate:
            d.fault = "error"
        if d.fault is not None:
            rule._injected += 1
            self.injected[d.fault] += 1
        return d

    def duplicate(self, queue_name: str) -> bool:
        """Queue-surface draw: should this publish fire twice?"""
        rule = self._rule_for(queue_name)
        if rule is None or rule.duplicate_rate <= 0:
            return False
        if self.rng.random() < rule.duplicate_rate:
            rule._injected += 1
            self.injected["duplicate"] += 1
            return True
        return False


# -- HTTP hop wrapping -------------------------------------------------------


class _FakeResponse:
    """The minimal response surface the dispatcher and sync proxy read."""

    def __init__(self, status: int,
                 body: bytes = b"chaos: injected backend error"):
        self.status = status
        self.headers: dict = {}
        self.content_type = "text/plain"
        self._body = body

    async def read(self) -> bytes:
        return self._body

    async def text(self) -> str:
        return self._body.decode()


class _ChaosRequestCtx:
    """Async context manager standing in for ``session.post(...)`` /
    ``session.request(...)``: applies the injector's decision, delegating
    to the real request only when the fault model says bytes move."""

    def __init__(self, injector: FaultInjector, url: str, factory):
        self._injector = injector
        self._url = url
        self._factory = factory
        self._inner = None

    async def __aenter__(self):
        d = self._injector.decide(self._url)
        if d.latency_s > 0:
            await asyncio.sleep(d.latency_s)
        if d.fault == "connect_error":
            # ClientConnectorError specifically (not the ClientConnectionError
            # base): that is what a real refused connection raises, and it is
            # the class the resilience retry gates key on to know the request
            # never reached the backend (gateway/router.py) — the base class
            # would make injected refusals behave unlike real ones.
            import types
            from urllib.parse import urlparse
            p = urlparse(self._url)
            key = types.SimpleNamespace(host=p.hostname or "", port=p.port,
                                        ssl=None, is_ssl=False)
            raise aiohttp.ClientConnectorError(
                key, OSError("chaos: connection refused"))
        if d.fault == "error":
            return _FakeResponse(d.status)
        self._inner = self._factory()
        resp = await self._inner.__aenter__()
        if d.fault == "drop":
            # The backend executed; the response is lost in transit. Drain
            # it first so the server side finishes cleanly, then present
            # the timeout the sender would have seen.
            await resp.read()
            await self._inner.__aexit__(None, None, None)
            self._inner = None
            raise asyncio.TimeoutError("chaos: response dropped")
        return resp

    async def __aexit__(self, *exc):
        if self._inner is not None:
            inner, self._inner = self._inner, None
            return await inner.__aexit__(*exc)
        return False


class ChaosSession:
    """Wraps a real ``aiohttp.ClientSession``, injecting faults on
    ``post``/``request``/``get``."""

    def __init__(self, inner, injector: FaultInjector):
        self._inner = inner
        self._injector = injector

    @property
    def closed(self) -> bool:
        return self._inner.closed

    def post(self, url, **kw):
        return _ChaosRequestCtx(self._injector, str(url),
                                lambda: self._inner.post(url, **kw))

    def get(self, url, **kw):
        return _ChaosRequestCtx(self._injector, str(url),
                                lambda: self._inner.get(url, **kw))

    def request(self, method, url, **kw):
        return _ChaosRequestCtx(
            self._injector, str(url),
            lambda: self._inner.request(method, url, **kw))

    async def close(self) -> None:
        await self._inner.close()


class ChaosSessionHolder:
    """Drop-in for ``utils.http.SessionHolder`` whose ``get()`` answers a
    fault-injecting session view over the real holder's session."""

    def __init__(self, inner, injector: FaultInjector):
        self._inner = inner
        self._injector = injector

    async def get(self) -> ChaosSession:
        return ChaosSession(await self._inner.get(), self._injector)

    async def close(self) -> None:
        await self._inner.close()


def wrap_platform_http(platform, injector: FaultInjector) -> None:
    """Install the injector on every HTTP hop the platform currently owns:
    each registered dispatcher's delivery session and the gateway's sync
    proxy session. Call AFTER routes are registered — dispatchers created
    later are not wrapped."""
    if getattr(platform, "dispatchers", None) is not None:
        for d in platform.dispatchers.dispatchers.values():
            d._sessions = ChaosSessionHolder(d._sessions, injector)
    platform.gateway._sessions = ChaosSessionHolder(
        platform.gateway._sessions, injector)


def wrap_publish_duplicates(platform, injector: FaultInjector) -> None:
    """Queue-surface duplicate injection: the store's publisher hook fires
    twice per ``duplicate`` draw, minting two broker messages for one task
    — the redelivery hazard lease expiry creates in production, on demand."""
    broker = platform.broker
    orig = broker.publish

    def publish(task) -> None:
        orig(task)
        if injector.duplicate(task.endpoint):
            orig(task)

    platform.store.set_publisher(publish)
