"""Kill/restart helpers for chaos scenarios — the process-level faults the
HTTP injector cannot express: a worker that is *gone* (its port answers
connection-refused) and a dispatcher that stops mid-delivery and later
comes back.

``RestartableBackend`` serves any aiohttp app on a stable port and can be
killed and restarted on THAT SAME port, so every URI the platform
recorded (task endpoints, registered backends) stays valid across the
outage — exactly what a pod restart behind a stable Service VIP looks
like.
"""

from __future__ import annotations

from aiohttp import web


class RestartableBackend:
    """An aiohttp app on a stable host:port with kill()/restart()."""

    def __init__(self, app: web.Application, host: str = "127.0.0.1",
                 port: int = 0):
        self.app = app
        self.host = host
        self.port = port
        self._runner: web.AppRunner | None = None

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    async def start(self) -> "RestartableBackend":
        self._runner = web.AppRunner(self.app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()
        if not self.port:
            self.port = self._runner.addresses[0][1]
        return self

    async def kill(self) -> None:
        """Stop serving: the port answers connection-refused until
        ``restart``. In-flight requests are aborted, like a real crash."""
        if self._runner is not None:
            await self._runner.cleanup()
            self._runner = None

    async def restart(self) -> None:
        if self._runner is not None:
            return  # already serving
        await self.start()

    @property
    def alive(self) -> bool:
        return self._runner is not None


async def kill_dispatcher(platform, queue_name: str):
    """Stop one dispatcher's delivery loops (in-flight deliveries are
    cancelled and their messages abandoned back to the broker — the crash
    path ``Dispatcher._run`` already implements). Returns the dispatcher
    so the caller can ``restart_dispatcher`` it."""
    d = platform.dispatchers.dispatchers[queue_name]
    await d.stop()
    return d


async def restart_dispatcher(platform, queue_name: str):
    """Bring a killed dispatcher back; its queue's backlog (including
    everything abandoned at kill time) drains normally."""
    d = platform.dispatchers.dispatchers[queue_name]
    await d.start()
    return d


async def kill_worker(backend: RestartableBackend) -> None:
    await backend.kill()


async def restart_worker(backend: RestartableBackend) -> None:
    await backend.restart()


def kill_shard_primary(platform, shard: int) -> None:
    """SIGKILL one shard primary of a sharded platform
    (``PlatformConfig(task_shards=N)``): its journal handle closes and
    every mutation refuses from this instant — no half-applied writes,
    exactly the window a process kill leaves. The next write routed to
    the shard performs the failover promotion inline (final journal
    drain → replica ``promote()`` minting the fencing epoch)."""
    platform.store.kill_shard_primary(shard)


def rebalance_slot(platform, slot: int, dest_shard: int) -> int:
    """Live rebalance under load: move one hash slot's keyspace range to
    ``dest_shard`` (``ShardedTaskStore.move_slot`` — bulk copy, then an
    atomic delta + ring flip under the old owner's lock). Returns tasks
    moved."""
    return platform.store.move_slot(slot, dest_shard)
