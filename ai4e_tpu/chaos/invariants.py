"""The invariants a chaos run must uphold, checked from the store's own
change feed — the same listener surface the gateway's long-poll waiters,
the result cache, and the admission goodput counter already ride.

Three claims, matching the platform's client contract:

1. **every accepted task terminates** — a POST that returned a TaskId is
   a promise; whatever faults the run injected, that task must reach a
   terminal status (completed / failed / dead-letter / expired), never
   sit in limbo forever;
2. **no task is lost** — an accepted task the store no longer knows AND
   that was never observed terminal vanished without a trace;
3. **no duplicate client-visible completion** — a task must enter the
   terminal set exactly once. A second terminal transition means a
   redelivered/duplicated execution overwrote a result the client may
   already have read.

Attach BEFORE traffic starts (listeners only see transitions from then
on); ``note_accepted`` records each TaskId the client was actually given.
"""

from __future__ import annotations

from ..taskstore import TaskNotFound, TaskStatus


class InvariantChecker:
    def __init__(self, shard_of=None, flight=None, dump_dir=None):
        """``shard_of`` (optional, ``shard_of(task_id) -> int``): the hash
        ring's owner function — when given, every verdict is ALSO
        available per shard (``by_shard``/``assert_shard_ok``), so a
        sharded chaos run can prove the invariants hold for each shard
        independently and for an exact keyspace range across a rebalance
        (``violations_for``).

        ``flight`` (optional ``observability.FlightRecorder``): dumped
        alongside the violation report when an assertion trips, so a red
        seeded run ships the request timelines that explain it.
        ``dump_dir`` overrides the artifact directory (default: the
        ``AI4E_CHAOS_DUMP_DIR`` env var, else ``/tmp/ai4e-chaos`` — the
        path CI's chaos-smoke job uploads on failure)."""
        self._store = None
        self.shard_of = shard_of
        self.flight = flight
        self.dump_dir = dump_dir
        self.accepted: set[str] = set()
        # First terminal status seen per task (listener feed).
        self.terminal: dict[str, str] = {}
        # (task_id, first_terminal, second_terminal) per violation.
        self.duplicate_completions: list[tuple[str, str, str]] = []

    def attach(self, store) -> "InvariantChecker":
        store.add_listener(self.on_task_event)
        self._store = store
        return self

    def note_accepted(self, task_id: str) -> None:
        """The client holds this TaskId (POST answered 200)."""
        self.accepted.add(task_id)

    def on_task_event(self, task) -> None:
        # May fire from any thread (store listeners run outside the lock);
        # dict/set mutation here is single-item and GIL-atomic.
        status = task.canonical_status
        if status not in TaskStatus.TERMINAL:
            return
        first = self.terminal.get(task.task_id)
        if first is None:
            self.terminal[task.task_id] = status
        else:
            self.duplicate_completions.append((task.task_id, first, status))

    # -- verdicts -----------------------------------------------------------

    def violations(self, task_ids=None) -> list[str]:
        """All violations, or — with ``task_ids`` — only those inside that
        keyspace range (the moved-slot check a rebalance scenario runs)."""
        wanted = None if task_ids is None else set(task_ids)
        out = []
        for tid in sorted(self.accepted):
            if wanted is not None and tid not in wanted:
                continue
            if tid in self.terminal:
                continue
            # Never seen terminal: distinguish "still limbo" from "gone".
            try:
                record = self._store.get(tid) if self._store else None
            except TaskNotFound:
                record = None
            if record is None:
                out.append(f"task {tid} LOST: accepted, never terminal, "
                           "and unknown to the store")
            else:
                out.append(f"task {tid} never reached a terminal status "
                           f"(stuck at {record.canonical_status!r})")
        for tid, first, second in self.duplicate_completions:
            if wanted is not None and tid not in wanted:
                continue
            out.append(f"task {tid} completed twice (client-visible): "
                       f"{first!r} then {second!r}")
        return out

    def assert_ok(self) -> None:
        problems = self.violations()
        if problems:
            dumped = self.dump_debug(problems)
            raise AssertionError(
                "chaos invariants violated"
                + (f" (debug artifacts: {dumped})" if dumped else "")
                + ":\n  " + "\n  ".join(problems))

    def dump_debug(self, problems: list[str]) -> str | None:
        """Write the violation report + the flight-recorder ring (when
        attached) + per-task summaries to the dump directory — the
        artifact CI uploads on a red chaos run, so the failure is
        debuggable without a local repro. Returns the directory, or
        None when dumping itself failed (a dump failure must never mask
        the violation it is documenting)."""
        import json
        import os
        import time

        directory = (self.dump_dir
                     or os.environ.get("AI4E_CHAOS_DUMP_DIR")
                     or "/tmp/ai4e-chaos")
        try:
            os.makedirs(directory, exist_ok=True)
            stamp = time.strftime("%Y%m%d-%H%M%S")
            report = {
                "violations": problems,
                "summary": self.summary(),
                "accepted": sorted(self.accepted),
                "terminal": dict(self.terminal),
                "duplicates": list(self.duplicate_completions),
            }
            with open(os.path.join(directory,
                                   f"violations-{stamp}.json"),
                      "w", encoding="utf-8") as fh:
                json.dump(report, fh, indent=1)
            if self.flight is not None:
                with open(os.path.join(directory, f"flight-{stamp}.json"),
                          "w", encoding="utf-8") as fh:
                    json.dump(self.flight.dump(), fh, indent=1)
            return directory
        except OSError:
            import logging
            logging.getLogger("ai4e_tpu.chaos").exception(
                "could not write chaos debug artifacts to %s", directory)
            return None

    def summary(self) -> dict:
        return {"accepted": len(self.accepted),
                "terminal": len(self.terminal),
                "duplicates": len(self.duplicate_completions)}

    # -- durable-truth verdicts (docs/durability.md) ------------------------

    def chain_divergences(self, store) -> list[str]:
        """Chain-verified replica convergence, per shard: every replica's
        verified-stream chain head must equal its primary's own-file head
        once the links have drained (equal heads ⇔ byte-identical
        absorbed history — the primary/replica divergence detector the
        record envelope exists for). ``store`` is the sharded facade;
        links are drained here so the check is not racing the tail loop.
        Replicas that never absorbed an enveloped line (fresh standby on
        an idle shard) are unanchored and skipped."""
        out: list[str] = []
        for group in getattr(store, "groups", ()):
            primary_head = getattr(group.active, "chain_head", None)
            if primary_head is None:
                continue
            for link in group.links:
                try:
                    link.drain()
                except Exception as exc:  # noqa: BLE001; ai4e: noqa[AIL005] — the exception IS the finding: it returns as a convergence violation
                    out.append(f"shard {group.index}: replica drain "
                               f"failed: {exc!r}")
                    continue
                head = link.standby.replica_chain_head
                if head is not None and head != primary_head:
                    out.append(
                        f"shard {group.index}: replica chain head {head} "
                        f"diverged from primary {primary_head}")
        return out

    def assert_replicas_converged(self, store) -> None:
        """Raise (with debug artifacts) unless every shard's replicas are
        chain-converged with their primary."""
        problems = self.chain_divergences(store)
        if problems:
            dumped = self.dump_debug(problems)
            raise AssertionError(
                "replica chain convergence violated"
                + (f" (debug artifacts: {dumped})" if dumped else "")
                + ":\n  " + "\n  ".join(problems))

    # -- per-shard verdicts (sharded runs; requires shard_of) ---------------

    def by_shard(self) -> dict[int, dict]:
        """Accepted/terminal/duplicate counts per shard — the invariant
        summary refactored onto the ring, so a shard-primary-kill run can
        prove the OTHER shards' keyspace was untouched."""
        if self.shard_of is None:
            raise ValueError("InvariantChecker was built without shard_of")
        out: dict[int, dict] = {}
        for tid in self.accepted:
            s = out.setdefault(self.shard_of(tid),
                               {"accepted": 0, "terminal": 0,
                                "duplicates": 0})
            s["accepted"] += 1
            if tid in self.terminal:
                s["terminal"] += 1
        for tid, _first, _second in self.duplicate_completions:
            s = out.setdefault(self.shard_of(tid),
                               {"accepted": 0, "terminal": 0,
                                "duplicates": 0})
            s["duplicates"] += 1
        return out

    def assert_shard_ok(self, shard: int) -> None:
        """Invariants restricted to ONE shard's keyspace: every accepted
        task of that shard terminal, none lost, zero duplicates."""
        if self.shard_of is None:
            raise ValueError("InvariantChecker was built without shard_of")
        ids = [tid for tid in self.accepted if self.shard_of(tid) == shard]
        problems = self.violations(ids)
        if problems:
            dumped = self.dump_debug(problems)
            raise AssertionError(
                f"shard {shard} invariants violated"
                + (f" (debug artifacts: {dumped})" if dumped else "")
                + ":\n  " + "\n  ".join(problems))
