"""Seeded filesystem fault injection for the journal's write path — the
disk-side sibling of ``injector.py``'s network faults.

The storage layer's claims (docs/durability.md) are about what survives
when the DISK misbehaves, not just when a process dies: a write that
lands only partially (torn write), a write the kernel refuses (ENOSPC),
an fsync that fails after the bytes were buffered (EIO), and — for
``fsync=never`` — a machine crash that drops the page cache out from
under an already-acknowledged flush. This module makes each of those a
deterministic, seeded event:

- ``DiskFaultInjector`` — seeded rule engine deciding per write/fsync;
- ``FaultyFile``        — wraps the store's real journal handle,
  applying decisions while delegating everything else (the store's
  ``_fsync_journal`` prefers a handle-level ``fsync()`` when present, so
  EIO-on-fsync injects without monkeypatching ``os.fsync``);
- ``attach_journal_faults`` — installs the wrapper on a live
  ``JournaledTaskStore``;
- ``lose_page_cache``   — the ``fsync=never`` crash model: truncate a
  journal FILE to a chosen byte (the prefix that "made it to the
  platter"), exactly what the crash-point sweep (``crashpoint.py``)
  drives across every boundary.

Production assemblies never construct any of this — chaos stays
test/bench tooling, same contract as the network injector.
"""

from __future__ import annotations

import errno as errno_mod
import random
from dataclasses import dataclass, field


@dataclass
class DiskFaultRule:
    """One fault schedule. ``op`` is ``"write"``, ``"flush"`` (fails the
    kernel handoff while the Python-side buffer RETAINS the bytes), or
    ``"fsync"``;
    ``after_ops`` skips that many matching operations first (a fault
    "mid-run", deterministically); ``rate`` draws seeded randomness
    instead (0 = fire every time once armed); ``times`` bounds how often
    the rule fires; ``torn_bytes`` makes a failing WRITE first persist
    that many bytes of the buffer — the short/torn-write shape (None =
    nothing persists)."""
    op: str = "write"
    errno: int = errno_mod.ENOSPC
    after_ops: int = 0
    rate: float = 0.0
    times: int | None = 1
    torn_bytes: int | None = None
    _seen: int = field(default=0, repr=False)
    _fired: int = field(default=0, repr=False)

    def exhausted(self) -> bool:
        return self.times is not None and self._fired >= self.times


class DiskFaultInjector:
    """Seeded decision source shared by every wrapped handle."""

    def __init__(self, seed: int = 0):
        self.seed = seed
        self.rng = random.Random(seed)
        self.rules: list[DiskFaultRule] = []
        self.injected: dict[str, int] = {}

    def add_rule(self, **spec) -> DiskFaultRule:
        rule = DiskFaultRule(**spec)
        self.rules.append(rule)
        return rule

    def clear(self) -> None:
        """Lift every fault (the recovery half of a scenario)."""
        self.rules = []

    def counts(self) -> dict:
        return dict(self.injected)

    def decide(self, op: str) -> DiskFaultRule | None:
        """First matching armed rule for this operation, or None."""
        for rule in self.rules:
            if rule.op != op or rule.exhausted():
                continue
            rule._seen += 1
            if rule._seen <= rule.after_ops:
                continue
            if rule.rate > 0 and self.rng.random() >= rule.rate:
                continue
            rule._fired += 1
            name = errno_mod.errorcode.get(rule.errno, "OSError")
            key = f"{op}:{name}"
            self.injected[key] = self.injected.get(key, 0) + 1
            return rule
        return None


class FaultyFile:
    """Wraps a real text-mode journal handle; ``JournaledTaskStore``
    writes/flushes/fsyncs through it unchanged until a rule fires."""

    def __init__(self, inner, injector: DiskFaultInjector):
        self._inner = inner
        self._injector = injector

    def write(self, data: str) -> int:
        rule = self._injector.decide("write")
        if rule is None:
            return self._inner.write(data)
        if rule.torn_bytes:
            # Torn write: a PREFIX of the buffer reaches the file before
            # the fault — the exact shape that leaves a partial line for
            # boot-salvage to truncate. Flush it through so the bytes are
            # really in the file, not just the wrapper's fiction.
            self._inner.write(data[:rule.torn_bytes])
            self._inner.flush()
        raise OSError(rule.errno, "chaos: injected disk fault on write")

    def flush(self) -> None:
        # op="flush" models the nastiest real-world shape: write()
        # buffered cleanly, the flush to the kernel fails, and the
        # BUFFER RETAINS the bytes — a later ordinary close() would
        # re-flush them behind the store's back (the resurrection the
        # store's discard-close exists to prevent).
        rule = self._injector.decide("flush")
        if rule is not None:
            raise OSError(rule.errno, "chaos: injected disk fault on flush")
        self._inner.flush()

    def fsync(self) -> None:
        # The store's _fsync_journal prefers this method when present —
        # the injection point for EIO-on-fsync.
        rule = self._injector.decide("fsync")
        if rule is not None:
            raise OSError(rule.errno, "chaos: injected disk fault on fsync")
        import os
        os.fsync(self._inner.fileno())

    def fileno(self) -> int:
        return self._inner.fileno()

    def close(self) -> None:
        self._inner.close()

    @property
    def closed(self) -> bool:
        return self._inner.closed

    def seek(self, *a):
        return self._inner.seek(*a)

    def tell(self):
        return self._inner.tell()


def attach_journal_faults(store, injector: DiskFaultInjector) -> None:
    """Install the injector on a live journaled store's append handle.
    Wraps the CURRENT handle — a compaction rewrite swaps in a fresh,
    unwrapped one (compaction under injected faults is its own scenario;
    re-attach after forcing one). Safe on a ``FollowerTaskStore`` in
    either role."""
    with store._lock:
        if store._journal is not None:
            store._journal = FaultyFile(store._journal, injector)
        raw = getattr(store, "_raw", None)
        if raw is not None and store._journal is not raw:
            store._raw = FaultyFile(raw, injector)


def lose_page_cache(journal_path: str, keep_bytes: int) -> int:
    """Machine-crash emulation for ``fsync=never``: the process died AND
    the kernel never wrote the tail — only ``keep_bytes`` of the journal
    survive. Returns the bytes dropped. The crash-point sweep drives this
    across every record boundary and seeded mid-record offsets
    (``crashpoint.py``)."""
    import os
    size = os.path.getsize(journal_path)
    keep = max(0, min(keep_bytes, size))
    with open(journal_path, "rb+") as fh:
        fh.truncate(keep)
    return size - keep
