"""Deterministic fault-injection harness (``docs/resilience.md``).

Test/bench tooling, never wired by ``PlatformConfig`` — production
assemblies carry no chaos code path. Three parts:

- ``injector``   — seeded ``FaultInjector`` + wrappers for the HTTP hop
  (error status / connection-refused / latency / dropped response) and
  the queue publish surface (duplicate delivery);
- ``harness``    — kill/restart helpers: ``RestartableBackend`` (a
  worker that dies and comes back on the same port),
  ``kill_dispatcher``/``restart_dispatcher``;
- ``invariants`` — ``InvariantChecker`` riding the store's change feed:
  every accepted task terminates, no task is lost, no duplicate
  client-visible completion — plus chain-verified replica convergence
  per shard (``assert_replicas_converged``);
- ``disk``       — seeded filesystem fault injection on the journal's
  write path (torn/short write, ENOSPC, EIO-on-fsync, lost page cache)
  — the storage-layer analogue of the network injector;
- ``crashpoint`` — the crash-point sweep: kill/restart a journaled
  store at every record boundary and seeded mid-record offsets, assert
  0 acknowledged-task loss / no conflicting state / replica
  convergence per reboot (docs/durability.md).

``bench.py --fault-rate R [--resilience]`` drives the same injector over
the full platform for the goodput-under-failure A/B.
"""

from .crashpoint import check_reboot, crash_offsets, drive_workload, sweep
from .disk import (DiskFaultInjector, DiskFaultRule, FaultyFile,
                   attach_journal_faults, lose_page_cache)
from .harness import (RestartableBackend, kill_dispatcher, kill_shard_primary,
                      kill_worker, rebalance_slot, restart_dispatcher,
                      restart_worker)
from .injector import (ChaosSession, ChaosSessionHolder, Decision,
                       FaultInjector, FaultRule, wrap_platform_http,
                       wrap_publish_duplicates)
from .invariants import InvariantChecker

__all__ = [
    "FaultInjector", "FaultRule", "Decision", "ChaosSession",
    "ChaosSessionHolder", "wrap_platform_http", "wrap_publish_duplicates",
    "RestartableBackend", "kill_dispatcher", "restart_dispatcher",
    "kill_worker", "restart_worker", "kill_shard_primary", "rebalance_slot",
    "InvariantChecker",
    "DiskFaultInjector", "DiskFaultRule", "FaultyFile",
    "attach_journal_faults", "lose_page_cache",
    "sweep", "drive_workload", "crash_offsets", "check_reboot",
]
