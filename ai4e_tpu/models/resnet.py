"""ResNet-50 species classifier (BASELINE.json config #4).

The reference's species-classification API wraps an opaque GPU container;
here it's a standard bottleneck ResNet in Flax, NHWC/bfloat16 for the MXU,
with BatchNorm in inference mode (running stats) so serving is stateless.
"""

from __future__ import annotations

from functools import partial

import flax.linen as nn
import jax
import jax.numpy as jnp


class Bottleneck(nn.Module):
    features: int
    strides: tuple = (1, 1)
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        norm = partial(nn.BatchNorm, use_running_average=True,
                       dtype=self.dtype)
        residual = x
        y = nn.Conv(self.features, (1, 1), use_bias=False, dtype=self.dtype)(x)
        y = norm()(y)
        y = nn.relu(y)
        y = nn.Conv(self.features, (3, 3), self.strides, padding="SAME",
                    use_bias=False, dtype=self.dtype)(y)
        y = norm()(y)
        y = nn.relu(y)
        y = nn.Conv(self.features * 4, (1, 1), use_bias=False,
                    dtype=self.dtype)(y)
        y = norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = nn.Conv(self.features * 4, (1, 1), self.strides,
                               use_bias=False, dtype=self.dtype)(residual)
            residual = norm()(residual)
        return nn.relu(y + residual)


class ResNet(nn.Module):
    stage_sizes: tuple = (3, 4, 6, 3)  # ResNet-50
    num_classes: int = 1000
    width: int = 64
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        x = x.astype(self.dtype)
        x = nn.Conv(self.width, (7, 7), (2, 2), padding=[(3, 3), (3, 3)],
                    use_bias=False, dtype=self.dtype)(x)
        x = nn.BatchNorm(use_running_average=True, dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, block_count in enumerate(self.stage_sizes):
            for j in range(block_count):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = Bottleneck(self.width * 2 ** i, strides,
                               dtype=self.dtype)(x)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(self.num_classes, dtype=jnp.float32)(x)
        return x  # (B, num_classes) float32 logits


def create_resnet50(rng=None, num_classes: int = 1000, image_size: int = 224):
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    model = ResNet(num_classes=num_classes)
    variables = model.init(rng, jnp.zeros((1, image_size, image_size, 3)))
    return model, variables
