from .detector import CenterNetDetector, create_detector, decode_detections
from .resnet import ResNet, create_resnet50
from .unet import UNet, create_unet, segment_logits_to_classes
from .vit import TP_RULES as VIT_TP_RULES, ViT, create_vit

__all__ = [
    "CenterNetDetector",
    "create_detector",
    "decode_detections",
    "ResNet",
    "create_resnet50",
    "UNet",
    "create_unet",
    "segment_logits_to_classes",
    "ViT",
    "VIT_TP_RULES",
    "create_vit",
]
