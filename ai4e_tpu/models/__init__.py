from .detector import CenterNetDetector, create_detector, decode_detections
from .moe import MOE_EP_RULES, MoEClassifier, create_moe
from .resnet import ResNet, create_resnet50
from .seqformer import SeqFormer, attention_for, create_seqformer
from .unet import UNet, create_unet, segment_logits_to_classes
from .vit import TP_RULES as VIT_TP_RULES, ViT, create_vit

__all__ = [
    "CenterNetDetector",
    "create_detector",
    "decode_detections",
    "MOE_EP_RULES",
    "MoEClassifier",
    "create_moe",
    "ResNet",
    "create_resnet50",
    "SeqFormer",
    "attention_for",
    "create_seqformer",
    "UNet",
    "create_unet",
    "segment_logits_to_classes",
    "ViT",
    "VIT_TP_RULES",
    "create_vit",
]
