"""Camera-trap animal detector (BASELINE.json config #3, the MegaDetector
slot).

The reference's camera-trap detection API is an opaque TF-1.9 GPU container
(``APIs/Charts/camera-trap/detection-async/prod-values.yaml:35-36``). Here the
detector is an anchor-free center-point model (CenterNet-style): a conv
backbone feeds three dense heads — center heatmap, box size, center offset.
Decoding is top-k over the heatmap, entirely in XLA-friendly ops (no
data-dependent shapes: fixed ``max_detections`` with a score mask), so the
whole forward + decode jits into one TPU program.

Classes follow MegaDetector: animal / person / vehicle.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp

NUM_CLASSES = 3  # animal, person, vehicle
MAX_DETECTIONS = 64


class _Stage(nn.Module):
    features: int
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        x = nn.Conv(self.features, (3, 3), (2, 2), padding="SAME",
                    use_bias=False, dtype=self.dtype)(x)
        x = nn.GroupNorm(num_groups=min(32, self.features), dtype=self.dtype)(x)
        x = nn.gelu(x)
        x = nn.Conv(self.features, (3, 3), padding="SAME", use_bias=False,
                    dtype=self.dtype)(x)
        x = nn.GroupNorm(num_groups=min(32, self.features), dtype=self.dtype)(x)
        return nn.gelu(x)


class CenterNetDetector(nn.Module):
    """Backbone stride 8; heads at 1/8 resolution."""

    num_classes: int = NUM_CLASSES
    widths: tuple = (64, 128, 256)
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        # x: (B, H, W, 3) in [0,1]
        x = x.astype(self.dtype)
        for w in self.widths:
            x = _Stage(w, self.dtype)(x)
        feat = nn.Conv(256, (3, 3), padding="SAME", dtype=self.dtype)(x)
        feat = nn.gelu(feat)
        heatmap = nn.Conv(self.num_classes, (1, 1), dtype=jnp.float32,
                          bias_init=nn.initializers.constant(-2.19))(feat)
        wh = nn.Conv(2, (1, 1), dtype=jnp.float32)(feat)
        offset = nn.Conv(2, (1, 1), dtype=jnp.float32)(feat)
        return {"heatmap": heatmap, "wh": wh, "offset": offset}


def _nms_heatmap(heat: jnp.ndarray) -> jnp.ndarray:
    """3x3 max-pool peak NMS: keep only local maxima (CenterNet's trick —
    replaces box NMS with a pooling op that XLA fuses for free)."""
    pooled = nn.max_pool(heat, (3, 3), strides=(1, 1), padding="SAME")
    return jnp.where(jnp.abs(pooled - heat) < 1e-6, heat, -jnp.inf)


def decode_detections(outputs: dict, stride: int = 8,
                      max_detections: int = MAX_DETECTIONS) -> dict:
    """Heatmap → fixed-size detection set. Static shapes: always returns
    ``max_detections`` rows; invalid rows carry score 0.

    Returns dict of (B, K, 4) boxes [y0, x0, y1, x1] in input pixels,
    (B, K) scores, (B, K) class ids.
    """
    heat = jax.nn.sigmoid(outputs["heatmap"])
    heat = _nms_heatmap(heat)
    b, h, w, c = heat.shape
    flat = heat.reshape(b, h * w * c)
    scores, idx = jax.lax.top_k(flat, max_detections)
    cls = idx % c
    pix = idx // c
    ys = (pix // w).astype(jnp.float32)
    xs = (pix % w).astype(jnp.float32)

    batch_ix = jnp.arange(b)[:, None]
    wh = outputs["wh"][batch_ix, pix // w, pix % w]          # (B, K, 2)
    offset = outputs["offset"][batch_ix, pix // w, pix % w]  # (B, K, 2)

    cy = (ys + offset[..., 0]) * stride
    cx = (xs + offset[..., 1]) * stride
    bh = jnp.abs(wh[..., 0]) * stride
    bw = jnp.abs(wh[..., 1]) * stride
    boxes = jnp.stack([cy - bh / 2, cx - bw / 2, cy + bh / 2, cx + bw / 2],
                      axis=-1)
    scores = jnp.where(jnp.isfinite(scores), scores, 0.0)
    return {"boxes": boxes, "scores": scores, "classes": cls}


def create_detector(rng=None, image_size: int = 512,
                    num_classes: int = NUM_CLASSES):
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    model = CenterNetDetector(num_classes=num_classes)
    params = model.init(rng, jnp.zeros((1, image_size, image_size, 3)))
    return model, params
