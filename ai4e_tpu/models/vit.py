"""Vision Transformer species classifier — the tensor/sequence-parallel
flagship.

The reference's species-classification slot is an opaque container; beyond
ResNet-50 (``resnet.py``) this ViT exists to exercise the parallelism the
platform treats as first-class (SURVEY.md §2 inventory): its dense dimensions
carry tensor-parallel sharding rules (``TP_RULES``) and its token dimension is
the sequence axis ring attention shards for long-context serving
(``parallel/ring_attention.py``).

Sharding rules follow the standard megatron split: attention QKV and MLP-up
column-split on ``tp``, attention-out and MLP-down row-split, so each block
needs exactly one psum on the residual — XLA inserts it from the specs.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

# param-path substring → PartitionSpec (consumed by parallel.shard_params)
TP_RULES = {
    "attn/qkv/kernel": P(None, "tp"),
    "attn/out/kernel": P("tp", None),
    "mlp/up/kernel": P(None, "tp"),
    "mlp/down/kernel": P("tp", None),
}


class Attention(nn.Module):
    dim: int
    heads: int
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        b, n, d = x.shape
        qkv = nn.Dense(3 * self.dim, use_bias=False, dtype=self.dtype,
                       name="qkv")(x)
        q, k, v = jnp.split(qkv.reshape(b, n, 3, self.heads,
                                        self.dim // self.heads), 3, axis=2)
        q, k, v = (t.squeeze(2).transpose(0, 2, 1, 3) for t in (q, k, v))
        scale = (self.dim // self.heads) ** -0.5
        attn = jax.nn.softmax((q @ k.transpose(0, 1, 3, 2)) * scale, axis=-1)
        out = (attn @ v).transpose(0, 2, 1, 3).reshape(b, n, self.dim)
        return nn.Dense(self.dim, dtype=self.dtype, name="out")(out)


class Mlp(nn.Module):
    dim: int
    expansion: int = 4
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        x = nn.Dense(self.dim * self.expansion, dtype=self.dtype, name="up")(x)
        x = nn.gelu(x)
        return nn.Dense(self.dim, dtype=self.dtype, name="down")(x)


class Block(nn.Module):
    dim: int
    heads: int
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        x = x + Attention(self.dim, self.heads, self.dtype,
                          name="attn")(nn.LayerNorm(dtype=self.dtype)(x))
        x = x + Mlp(self.dim, dtype=self.dtype,
                    name="mlp")(nn.LayerNorm(dtype=self.dtype)(x))
        return x


class ViT(nn.Module):
    num_classes: int = 1000
    patch: int = 16
    dim: int = 384
    depth: int = 6
    heads: int = 6
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        # x: (B, H, W, 3)
        x = x.astype(self.dtype)
        x = nn.Conv(self.dim, (self.patch, self.patch),
                    strides=(self.patch, self.patch), name="embed",
                    dtype=self.dtype)(x)
        b, h, w, d = x.shape
        x = x.reshape(b, h * w, d)
        pos = self.param("pos_embed", nn.initializers.normal(0.02),
                         (1, h * w, d), jnp.float32)
        x = x + pos.astype(self.dtype)
        for i in range(self.depth):
            x = Block(self.dim, self.heads, self.dtype, name=f"block{i}")(x)
        x = nn.LayerNorm(dtype=self.dtype)(x)
        x = x.mean(axis=1)
        return nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)


def create_vit(rng=None, num_classes: int = 1000, image_size: int = 224,
               patch: int = 16, dim: int = 384, depth: int = 6,
               heads: int = 6):
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    model = ViT(num_classes=num_classes, patch=patch, dim=dim, depth=depth,
                heads=heads)
    params = model.init(rng, jnp.zeros((1, image_size, image_size, 3)))
    return model, params
