"""Land-cover semantic segmentation UNet — the platform's flagship model.

The reference serves land-cover segmentation as an opaque TF-1.9 GPU container
(``APIManagement/create_sync_api_management_api.sh:38-92`` registers its
classify/tile operations; the model itself lives outside the repo). Here the
model is a first-class JAX citizen: a compact UNet whose shapes are chosen for
the MXU — channel counts in multiples of 128, bfloat16 activations, NHWC
layout (TPU-native conv layout), static shapes per tile bucket.

Classes follow the AI4E land-cover API: water / forest / field / impervious.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp

NUM_CLASSES = 4
TILE = 256  # default tile edge (the land-cover API's unit of work)


class ConvBlock(nn.Module):
    features: int
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        for _ in range(2):
            x = nn.Conv(self.features, (3, 3), padding="SAME",
                        dtype=self.dtype, use_bias=False)(x)
            # GroupNorm over channels: batch-size independent (serving batches
            # vary by bucket) and fuses well under XLA.
            x = nn.GroupNorm(num_groups=min(32, self.features),
                             dtype=self.dtype)(x)
            x = nn.gelu(x)
        return x


class UNet(nn.Module):
    """Encoder-decoder with skip connections.

    ``widths`` start at 64 and stay in MXU-friendly multiples; downsampling by
    strided conv (cheaper than pool+conv on TPU), upsampling by
    ``jax.image.resize`` + 1x1 conv (avoids checkerboard transposed convs and
    keeps XLA fusion simple).
    """

    num_classes: int = NUM_CLASSES
    widths: tuple = (64, 128, 256, 512)
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        # x: (B, H, W, 3) float32 in [0, 1]
        x = x.astype(self.dtype)
        skips = []
        for i, w in enumerate(self.widths):
            x = ConvBlock(w, self.dtype)(x)
            if i < len(self.widths) - 1:
                skips.append(x)
                x = nn.Conv(w, (3, 3), strides=(2, 2), padding="SAME",
                            dtype=self.dtype, use_bias=False)(x)
        for w, skip in zip(reversed(self.widths[:-1]), reversed(skips)):
            b, h, s, c = skip.shape
            x = jax.image.resize(x, (x.shape[0], h, s, x.shape[3]), "nearest")
            x = nn.Conv(w, (1, 1), dtype=self.dtype, use_bias=False)(x)
            x = jnp.concatenate([x, skip], axis=-1)
            x = ConvBlock(w, self.dtype)(x)
        logits = nn.Conv(self.num_classes, (1, 1), dtype=jnp.float32)(x)
        return logits  # (B, H, W, num_classes), float32 for stable softmax


def create_unet(rng=None, tile: int = TILE, num_classes: int = NUM_CLASSES,
                widths: tuple = (64, 128, 256, 512)):
    """Init a UNet and return (model, params)."""
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    model = UNet(num_classes=num_classes, widths=widths)
    params = model.init(rng, jnp.zeros((1, tile, tile, 3), jnp.float32))
    return model, params


def segment_logits_to_classes(logits: jnp.ndarray) -> jnp.ndarray:
    """Per-pixel argmax → uint8 class map (the API's response payload)."""
    return jnp.argmax(logits, axis=-1).astype(jnp.uint8)
