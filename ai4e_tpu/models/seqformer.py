"""SeqFormer — long-context transformer encoder served with sequence
parallelism.

The reference has no sequence dimension anywhere (SURVEY.md §5 long-context:
its unit of work is one image tile); this model family fills the long-context
slot the TPU framework treats as first-class. Inputs are long feature
sequences — e.g. embedded acoustic-monitoring or satellite time series — of
shape ``(S, input_dim)`` with S in the tens of thousands; attention over them
is computed with **ring attention** (K/V blocks rotating over the mesh's
``sp`` axis via ``ppermute``) or **Ulysses all-to-all**
(``parallel/ring_attention.py``), so a sequence's O(S²) attention is sharded
S/n-per-device and the activations never materialise full S×S scores.

The attention strategy is injected as a plain callable: ``create_seqformer``
picks ring/Ulysses over the given mesh when its ``sp`` axis is >1 and plain
full attention otherwise, so the same module serves single-chip and
sequence-parallel deployments.
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np


class SeqAttention(nn.Module):
    dim: int
    heads: int
    attn_fn: Callable  # (q, k, v) -> o, all (B, H, S, D)
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        b, s, d = x.shape
        head_dim = self.dim // self.heads
        qkv = nn.Dense(3 * self.dim, use_bias=False, dtype=self.dtype,
                       name="qkv")(x)
        qkv = qkv.reshape(b, s, 3, self.heads, head_dim)
        q, k, v = (qkv[:, :, i].transpose(0, 2, 1, 3) for i in range(3))
        o = self.attn_fn(q, k, v)
        o = o.transpose(0, 2, 1, 3).reshape(b, s, self.dim)
        return nn.Dense(d, use_bias=False, dtype=self.dtype, name="out")(o)


class SeqBlock(nn.Module):
    dim: int
    heads: int
    attn_fn: Callable
    mlp_ratio: int = 4
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        x = x + SeqAttention(self.dim, self.heads, self.attn_fn,
                             dtype=self.dtype, name="attn")(nn.LayerNorm()(x))
        h = nn.LayerNorm()(x)
        h = nn.Dense(self.dim * self.mlp_ratio, dtype=self.dtype,
                     name="mlp_up")(h)
        h = nn.gelu(h)
        h = nn.Dense(self.dim, dtype=self.dtype, name="mlp_down")(h)
        return x + h


class SeqFormer(nn.Module):
    """Encoder over (B, S, input_dim) float features — or, with
    ``vocab_size`` set, over (B, S) integer token ids — → (B, num_classes).

    Token mode is the production long-context wire: clients ship ids
    (2 bytes/token) and the embedding lookup happens on-device, instead of
    shipping pre-embedded S×D float features (128 bytes/token at D=64 f16).
    On a remote-attached chip that is the difference between a link-bound
    and a compute-bound service (r3 measured the feature wire saturating
    the tunnel at 524 kB/request)."""

    seq_len: int
    input_dim: int
    dim: int = 128
    depth: int = 2
    heads: int = 8
    num_classes: int = 16
    attn_fn: Callable = None  # injected; None → full attention
    dtype: jnp.dtype = jnp.bfloat16
    vocab_size: int | None = None  # None → float features, else token ids

    @nn.compact
    def __call__(self, x):
        from ..parallel.ring_attention import reference_attention
        attn_fn = self.attn_fn or reference_attention
        if self.vocab_size is not None:
            h = nn.Embed(self.vocab_size, self.dim, dtype=self.dtype,
                         name="embed")(x)
        else:
            h = nn.Dense(self.dim, dtype=self.dtype, name="embed")(x)
        pos = self.param("pos_emb", nn.initializers.normal(0.02),
                         (1, self.seq_len, self.dim))
        h = h + pos.astype(self.dtype)
        for i in range(self.depth):
            h = SeqBlock(self.dim, self.heads, attn_fn, dtype=self.dtype,
                         name=f"block{i}")(h)
        h = nn.LayerNorm()(h.mean(axis=1))  # pool over the sequence
        return nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(h)


def attention_for(mesh=None, strategy: str = "auto", causal: bool = False,
                  batch_axes=("dp", "fsdp")) -> Callable:
    """Pick the attention implementation for a mesh.

    ``auto`` → ring when the mesh's sp axis is >1, else the fused flash
    kernel; ``ring`` / ``ulysses`` force the parallel paths; ``flash``
    forces the single-device Pallas kernel (``ops/pallas/flash_attention``);
    ``full`` forces plain materialised attention (the correctness oracle).
    """
    from ..ops.pallas import flash_attention
    from ..parallel.ring_attention import (
        reference_attention,
        ring_attention,
        ulysses_attention,
    )
    valid = ("auto", "ring", "ulysses", "flash", "full")
    if strategy not in valid:
        raise ValueError(f"unknown attention strategy {strategy!r}; "
                         f"valid: {valid}")
    sp = mesh.shape.get("sp", 1) if mesh is not None else 1
    if strategy == "auto":
        strategy = "ring" if sp > 1 else "flash"
    if strategy == "full":
        return partial(reference_attention, causal=causal)
    if strategy == "flash":
        return partial(flash_attention, causal=causal)
    if mesh is None or sp <= 1:
        raise ValueError(f"{strategy} attention needs a mesh with sp > 1")
    fn = {"ring": ring_attention, "ulysses": ulysses_attention}[strategy]
    return partial(fn, mesh=mesh, causal=causal, batch_axes=batch_axes)


def create_seqformer(rng=None, seq_len: int = 4096, input_dim: int = 64,
                     dim: int = 128, depth: int = 2, heads: int = 8,
                     num_classes: int = 16, mesh=None,
                     attention: str = "auto", causal: bool = False,
                     vocab_size: int | None = None):
    """Build model + params. With a sequence-parallel mesh the sequence must
    divide the sp axis size (static shapes — SPMD). ``vocab_size`` switches
    the input contract to (B, S) token ids with on-device embedding."""
    if mesh is not None:
        sp = mesh.shape.get("sp", 1)
        if seq_len % max(sp, 1):
            raise ValueError(f"seq_len {seq_len} not divisible by sp={sp}")
    model = SeqFormer(seq_len=seq_len, input_dim=input_dim, dim=dim,
                      depth=depth, heads=heads, num_classes=num_classes,
                      attn_fn=attention_for(mesh, attention, causal),
                      vocab_size=vocab_size)
    # Init with a param-free stub attention (identity on q — same output
    # shape): the strategy carries no params, so the tree is identical, and
    # init neither materialises O(S²) scores for long sequences nor gets
    # constrained to the mesh's dp size by the batch-1 forward.
    init_model = model.clone(attn_fn=lambda q, k, v: q)
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    init_x = (np.zeros((1, seq_len), np.int32) if vocab_size is not None
              else np.zeros((1, seq_len, input_dim), np.float32))
    params = init_model.init(rng, init_x)
    return model, params
