"""SeqFormer — long-context transformer encoder served with sequence
parallelism.

The reference has no sequence dimension anywhere (SURVEY.md §5 long-context:
its unit of work is one image tile); this model family fills the long-context
slot the TPU framework treats as first-class. Inputs are long feature
sequences — e.g. embedded acoustic-monitoring or satellite time series — of
shape ``(S, input_dim)`` with S in the tens of thousands; attention over them
is computed with **ring attention** (K/V blocks rotating over the mesh's
``sp`` axis via ``ppermute``) or **Ulysses all-to-all**
(``parallel/ring_attention.py``), so a sequence's O(S²) attention is sharded
S/n-per-device and the activations never materialise full S×S scores.

The attention strategy is injected as a plain callable: ``create_seqformer``
picks ring/Ulysses over the given mesh when its ``sp`` axis is >1 and plain
full attention otherwise, so the same module serves single-chip and
sequence-parallel deployments.
"""

from __future__ import annotations

from functools import partial
from typing import Callable

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np


class SeqAttention(nn.Module):
    dim: int
    heads: int
    attn_fn: Callable  # (q, k, v) -> o, all (B, H, S, D)
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        b, s, d = x.shape
        head_dim = self.dim // self.heads
        qkv = nn.Dense(3 * self.dim, use_bias=False, dtype=self.dtype,
                       name="qkv")(x)
        qkv = qkv.reshape(b, s, 3, self.heads, head_dim)
        q, k, v = (qkv[:, :, i].transpose(0, 2, 1, 3) for i in range(3))
        o = self.attn_fn(q, k, v)
        o = o.transpose(0, 2, 1, 3).reshape(b, s, self.dim)
        return nn.Dense(d, use_bias=False, dtype=self.dtype, name="out")(o)


class SeqBlock(nn.Module):
    dim: int
    heads: int
    attn_fn: Callable
    mlp_ratio: int = 4
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        x = x + SeqAttention(self.dim, self.heads, self.attn_fn,
                             dtype=self.dtype, name="attn")(nn.LayerNorm()(x))
        h = nn.LayerNorm()(x)
        h = nn.Dense(self.dim * self.mlp_ratio, dtype=self.dtype,
                     name="mlp_up")(h)
        h = nn.gelu(h)
        h = nn.Dense(self.dim, dtype=self.dtype, name="mlp_down")(h)
        return x + h


class SeqFormer(nn.Module):
    """Encoder over (B, S, input_dim) float features — or, with
    ``vocab_size`` set, over (B, S) integer token ids — → (B, num_classes).

    Token mode is the production long-context wire: clients ship ids
    (2 bytes/token) and the embedding lookup happens on-device, instead of
    shipping pre-embedded S×D float features (128 bytes/token at D=64 f16).
    On a remote-attached chip that is the difference between a link-bound
    and a compute-bound service (r3 measured the feature wire saturating
    the tunnel at 524 kB/request)."""

    seq_len: int
    input_dim: int
    dim: int = 128
    depth: int = 2
    heads: int = 8
    num_classes: int = 16
    attn_fn: Callable = None  # injected; None → full attention
    dtype: jnp.dtype = jnp.bfloat16
    vocab_size: int | None = None  # None → float features, else token ids

    @nn.compact
    def __call__(self, x):
        from ..parallel.ring_attention import reference_attention
        attn_fn = self.attn_fn or reference_attention
        if self.vocab_size is not None:
            h = nn.Embed(self.vocab_size, self.dim, dtype=self.dtype,
                         name="embed")(x)
        else:
            h = nn.Dense(self.dim, dtype=self.dtype, name="embed")(x)
        pos = self.param("pos_emb", nn.initializers.normal(0.02),
                         (1, self.seq_len, self.dim))
        h = h + pos.astype(self.dtype)
        for i in range(self.depth):
            h = SeqBlock(self.dim, self.heads, attn_fn, dtype=self.dtype,
                         name=f"block{i}")(h)
        h = nn.LayerNorm()(h.mean(axis=1))  # pool over the sequence
        return nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(h)


class _LMBlock(nn.Module):
    """One causal decoder block with the two attention entry points the
    serving runtime needs: ``prefill`` (full causal attention over the
    prompt, returning the K/V it computed) and ``step`` (one token per
    sequence against a K/V cache, returning the cache with the new
    token's K/V written at ``position``). Both run through the SAME
    parameters — ``setup`` instead of ``nn.compact`` so the two methods
    share the module tree."""

    dim: int
    heads: int
    dtype: jnp.dtype = jnp.float32

    def setup(self):
        self.ln1 = nn.LayerNorm(name="ln1")
        self.qkv = nn.Dense(3 * self.dim, use_bias=False, dtype=self.dtype,
                            name="qkv")
        self.proj = nn.Dense(self.dim, use_bias=False, dtype=self.dtype,
                             name="proj")
        self.ln2 = nn.LayerNorm(name="ln2")
        self.mlp_up = nn.Dense(self.dim * 4, dtype=self.dtype, name="mlp_up")
        self.mlp_down = nn.Dense(self.dim, dtype=self.dtype, name="mlp_down")

    def prefill(self, x, mask):
        """x: (B, S, D); mask: (B, S) True on real tokens. Returns
        ``(y, k, v)`` with k/v of shape (B, H, S, hd) — the block's
        contribution to the sequence's KV cache."""
        b, s, _ = x.shape
        hd = self.dim // self.heads
        h = self.ln1(x)
        qkv = self.qkv(h).reshape(b, s, 3, self.heads, hd)
        q, k, v = (qkv[:, :, i].transpose(0, 2, 1, 3) for i in range(3))
        scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) / jnp.sqrt(hd)
        causal = jnp.tril(jnp.ones((s, s), bool))
        keep = causal[None, None] & mask[:, None, None, :]
        scores = jnp.where(keep, scores, jnp.asarray(-1e30, scores.dtype))
        o = jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(scores, axis=-1), v)
        x = x + self.proj(o.transpose(0, 2, 1, 3).reshape(b, s, self.dim))
        x = x + self.mlp_down(nn.gelu(self.mlp_up(self.ln2(x))))
        return x, k, v

    def step(self, x, k_cache, v_cache, position):
        """One decode step over the slot pool. x: (S, D) — one new token
        per slot; k_cache/v_cache: (S, H, L, hd); position: (S,) — the
        cache index the new token's K/V lands at. Returns ``(y, k, v)``
        with the caches updated via a one-hot scatter (SPMD-friendly: no
        per-slot dynamic slices)."""
        s, _ = x.shape
        hd = self.dim // self.heads
        length = k_cache.shape[2]
        h = self.ln1(x)
        qkv = self.qkv(h).reshape(s, 3, self.heads, hd)
        q, k_new, v_new = qkv[:, 0], qkv[:, 1], qkv[:, 2]  # (S, H, hd)
        oh = jax.nn.one_hot(position, length, dtype=k_cache.dtype)  # (S, L)
        k_cache = (k_cache * (1.0 - oh)[:, None, :, None]
                   + k_new[:, :, None, :] * oh[:, None, :, None])
        v_cache = (v_cache * (1.0 - oh)[:, None, :, None]
                   + v_new[:, :, None, :] * oh[:, None, :, None])
        scores = jnp.einsum("shd,shld->shl", q, k_cache) / jnp.sqrt(hd)
        valid = (jnp.arange(length)[None, :]
                 <= position[:, None])  # keys at or before the new token
        scores = jnp.where(valid[:, None, :], scores,
                           jnp.asarray(-1e30, scores.dtype))
        o = jnp.einsum("shl,shld->shd", jax.nn.softmax(scores, axis=-1),
                       v_cache)
        x = x + self.proj(o.reshape(s, self.dim))
        x = x + self.mlp_down(nn.gelu(self.mlp_up(self.ln2(x))))
        return x, k_cache, v_cache


class SeqFormerLM(nn.Module):
    """Causal token LM over the SeqFormer block stack — the
    autoregressive serving shape (``runtime/decode.py``). Two entry
    points, applied via ``method=``:

    - ``prefill(tokens (B, P), length (B,))`` → ``(next-token ids (B,),
      k, v)`` with k/v of shape (depth, B, H, P, hd) — the prompt's KV
      block, inserted into a slot of the pooled cache by the decode
      runtime (``runtime/kvcache.py``);
    - ``decode_step(tokens (S,), k (depth, S, H, L, hd), v, position
      (S,))`` → ``(next-token ids (S,), k, v)`` — ONE token for every
      slot in the pool per call, inactive slots riding along masked
      (their cache rows are garbage a later prefill overwrites).

    Greedy decoding is computed on-device (argmax over the tied-embedding
    logits) so each step ships S int32s back to the host, not S×V logits.
    """

    vocab_size: int
    max_len: int
    dim: int = 64
    depth: int = 2
    heads: int = 4
    dtype: jnp.dtype = jnp.float32

    def setup(self):
        self.embed = nn.Embed(self.vocab_size, self.dim, dtype=self.dtype,
                              name="embed")
        self.pos_emb = self.param("pos_emb", nn.initializers.normal(0.02),
                                  (self.max_len, self.dim))
        self.blocks = [_LMBlock(self.dim, self.heads, dtype=self.dtype,
                                name=f"block{i}") for i in range(self.depth)]
        self.ln_f = nn.LayerNorm(name="ln_f")

    def _logits(self, h):
        # Tied embedding head: attend() reuses the embedding matrix, so
        # the LM head adds no parameters beyond the encoder families'.
        return self.embed.attend(self.ln_f(h).astype(jnp.float32)
                                 .astype(self.dtype))

    def prefill(self, tokens, length):
        b, p = tokens.shape
        h = self.embed(tokens) + self.pos_emb[None, :p].astype(self.dtype)
        mask = jnp.arange(p)[None, :] < length[:, None]
        ks, vs = [], []
        for blk in self.blocks:
            h, k, v = blk.prefill(h, mask)
            ks.append(k)
            vs.append(v)
        last = jnp.take_along_axis(
            h, (length - 1)[:, None, None].astype(jnp.int32), axis=1)[:, 0]
        next_token = jnp.argmax(self._logits(last), axis=-1).astype(jnp.int32)
        return next_token, jnp.stack(ks), jnp.stack(vs)

    def decode_step(self, tokens, k_cache, v_cache, position):
        h = (self.embed(tokens)
             + self.pos_emb[position].astype(self.dtype))  # (S, D)
        new_k, new_v = [], []
        for i, blk in enumerate(self.blocks):
            h, k, v = blk.step(h, k_cache[i], v_cache[i], position)
            new_k.append(k)
            new_v.append(v)
        next_token = jnp.argmax(self._logits(h), axis=-1).astype(jnp.int32)
        return next_token, jnp.stack(new_k), jnp.stack(new_v)


def create_seqformer_lm(rng=None, vocab_size: int = 512, max_len: int = 256,
                        dim: int = 64, depth: int = 2, heads: int = 4):
    """Build the causal LM + params for the continuous-batching decode
    path. ``max_len`` is the KV-cache depth per slot — prompt plus
    generated tokens must fit under it (``docs/streaming.md`` has the
    memory math)."""
    if dim % heads:
        raise ValueError(f"dim {dim} not divisible by heads {heads}")
    model = SeqFormerLM(vocab_size=vocab_size, max_len=max_len, dim=dim,
                        depth=depth, heads=heads)
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    init_p = min(8, max_len)
    params = model.init(rng, np.zeros((1, init_p), np.int32),
                        np.ones((1,), np.int32), method=SeqFormerLM.prefill)
    return model, params


def attention_for(mesh=None, strategy: str = "auto", causal: bool = False,
                  batch_axes=("dp", "fsdp")) -> Callable:
    """Pick the attention implementation for a mesh.

    ``auto`` → ring when the mesh's sp axis is >1, else the fused flash
    kernel; ``ring`` / ``ulysses`` force the parallel paths; ``flash``
    forces the single-device Pallas kernel (``ops/pallas/flash_attention``);
    ``full`` forces plain materialised attention (the correctness oracle).
    """
    from ..ops.pallas import flash_attention
    from ..parallel.ring_attention import (
        reference_attention,
        ring_attention,
        ulysses_attention,
    )
    valid = ("auto", "ring", "ulysses", "flash", "full")
    if strategy not in valid:
        raise ValueError(f"unknown attention strategy {strategy!r}; "
                         f"valid: {valid}")
    sp = mesh.shape.get("sp", 1) if mesh is not None else 1
    if strategy == "auto":
        strategy = "ring" if sp > 1 else "flash"
    if strategy == "full":
        return partial(reference_attention, causal=causal)
    if strategy == "flash":
        return partial(flash_attention, causal=causal)
    if mesh is None or sp <= 1:
        raise ValueError(f"{strategy} attention needs a mesh with sp > 1")
    fn = {"ring": ring_attention, "ulysses": ulysses_attention}[strategy]
    return partial(fn, mesh=mesh, causal=causal, batch_axes=batch_axes)


def create_seqformer(rng=None, seq_len: int = 4096, input_dim: int = 64,
                     dim: int = 128, depth: int = 2, heads: int = 8,
                     num_classes: int = 16, mesh=None,
                     attention: str = "auto", causal: bool = False,
                     vocab_size: int | None = None):
    """Build model + params. With a sequence-parallel mesh the sequence must
    divide the sp axis size (static shapes — SPMD). ``vocab_size`` switches
    the input contract to (B, S) token ids with on-device embedding."""
    if mesh is not None:
        sp = mesh.shape.get("sp", 1)
        if seq_len % max(sp, 1):
            raise ValueError(f"seq_len {seq_len} not divisible by sp={sp}")
    model = SeqFormer(seq_len=seq_len, input_dim=input_dim, dim=dim,
                      depth=depth, heads=heads, num_classes=num_classes,
                      attn_fn=attention_for(mesh, attention, causal),
                      vocab_size=vocab_size)
    # Init with a param-free stub attention (identity on q — same output
    # shape): the strategy carries no params, so the tree is identical, and
    # init neither materialises O(S²) scores for long sequences nor gets
    # constrained to the mesh's dp size by the batch-1 forward.
    init_model = model.clone(attn_fn=lambda q, k, v: q)
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    init_x = (np.zeros((1, seq_len), np.int32) if vocab_size is not None
              else np.zeros((1, seq_len, input_dim), np.float32))
    params = init_model.init(rng, init_x)
    return model, params
