"""Mixture-of-Experts sequence classifier — the expert-parallel (``ep``)
model family.

The reference has no MoE (or any model internals — containers are opaque);
this family exists so the mesh's ``ep`` axis (``parallel/sharding.py`` AXES)
is exercised by a real servable, the same way seqformer exercises ``sp``.

Design (TPU-first):

- **Routing** is top-1 token-choice with two static dispatch strategies
  (``MoEFFN.dispatch``): ``dense`` — every expert runs every token, the gate
  zeroes the losers (E× FLOPs, zero bookkeeping, bitwise deterministic;
  right for small E where the win is sharding) — and ``capacity`` — the
  GShard/Switch production shape: grouped tokens, per-group static expert
  capacity, cumsum slot assignment (no sorts, no dynamic shapes), FFN cost
  ~``capacity_factor·T`` token-passes, overflow tokens dropped to the
  residual. Both compile to fixed shapes; XLA never sees data-dependent
  control flow.
- **Expert parallelism**: expert weight tensors are (E, D, H) with
  ``P("ep", None, None)`` — each ep shard holds E/ep experts and computes
  only their einsum slices; the token-combine contraction reduces over E, so
  XLA inserts one ``psum`` over ``ep`` per MoE layer (ICI traffic: one (B,
  S, D) activation — the standard MoE all-reduce pattern).
- Everything else (attention, norms) replicates over ``ep``, so the family
  composes with dp/fsdp/tp exactly like the dense families.
"""

from __future__ import annotations

from typing import Callable

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

# Param-path rules for shard_params: expert-major tensors over ep.
MOE_EP_RULES = {
    "moe/up": P("ep", None, None),
    "moe/down": P("ep", None, None),
}


class MoEFFN(nn.Module):
    """Top-1 token-choice MoE FFN with two dispatch strategies:

    - ``dense`` — every expert runs every token, gate zeroes the losers.
      E× the FLOPs, zero bookkeeping, bitwise deterministic; right for
      small E where the win is sharding, not sparsity.
    - ``capacity`` — the production MoE shape (GShard/Switch style): each
      expert processes at most ``C = ceil(T/E · capacity_factor)`` tokens,
      gathered with a static one-hot dispatch tensor (cumsum position
      assignment — no sorts, no dynamic shapes). FFN FLOPs drop from
      ``E·T`` to ``E·C ≈ capacity_factor·T`` token-passes; overflow tokens
      are dropped (their residual branch passes through unchanged).
    Expert tensors shard over ``ep`` either way.
    """

    dim: int
    num_experts: int
    mlp_ratio: int = 4
    dispatch: str = "dense"
    capacity_factor: float = 1.25
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):  # (B, S, D)
        hidden = self.dim * self.mlp_ratio
        # Router in float32: gate ordering must not wobble with bf16 noise.
        logits = nn.Dense(self.num_experts, dtype=jnp.float32,
                          name="router")(x.astype(jnp.float32))
        gates = jax.nn.softmax(logits, axis=-1)            # (B, S, E)
        top = jnp.argmax(gates, axis=-1)                   # (B, S)
        top_gate = jnp.max(gates, axis=-1)                 # (B, S)

        up = self.param("up", nn.initializers.lecun_normal(),
                        (self.num_experts, self.dim, hidden))
        down = self.param("down", nn.initializers.lecun_normal(),
                          (self.num_experts, hidden, self.dim))

        if self.dispatch == "capacity":
            y = self._capacity_dispatch(x, top, top_gate, up, down)
        elif self.dispatch == "dense":
            onehot = (jax.nn.one_hot(top, self.num_experts,
                                     dtype=jnp.float32)
                      * top_gate[..., None])
            xb = x.astype(self.dtype)
            # e is sharded over ep: each shard computes its experts...
            h = jnp.einsum("bsd,edh->bseh", xb, up.astype(self.dtype))
            h = nn.gelu(h)
            out = jnp.einsum("bseh,ehd->bsed", h, down.astype(self.dtype))
            # ...and this contraction reduces over e → one psum over ep.
            y = jnp.einsum("bsed,bse->bsd", out.astype(jnp.float32), onehot)
        else:
            # Validate where the field is consumed, not only in create_moe:
            # a typo'd strategy must not silently run the dense path.
            raise ValueError(f"unknown MoE dispatch {self.dispatch!r}; "
                             "expected 'dense' or 'capacity'")
        return y.astype(x.dtype), top

    GROUP = 128  # GShard-style group size: dispatch cost is linear in T
                 # (~GROUP·cf·T elements), never quadratic

    def _capacity_dispatch(self, x, top, top_gate, up, down):
        b, s, d = x.shape
        e = self.num_experts
        # Tokens are dispatched in fixed-size GROUPS with per-group capacity
        # (the GShard (G, S_g, E, C) shape): the one-hot dispatch/combine
        # tensors cost G·S_g·E·C = T·S_g·cf elements — linear in T for the
        # fixed S_g — where a flat-T dispatch would be cf·T² and dwarf the
        # expert matmuls it's routing for.
        sg = min(s, self.GROUP)
        while s % sg:
            sg -= 1
        if s > 8 and sg < 8:
            # A prime-ish sequence length would collapse to one-token
            # groups: capacity becomes vacuous (cap >= 1 drops nothing) and
            # the dispatch overhead exceeds the dense path it should beat.
            raise ValueError(
                f"seq_len {s} has no group divisor >= 8; pad the sequence "
                "(e.g. to a multiple of 128) for capacity dispatch")
        g = (b * s) // sg
        cap = max(1, int(np.ceil(sg / e * self.capacity_factor)))

        xg = x.reshape(g, sg, d)
        oh = jax.nn.one_hot(top.reshape(g, sg), e,
                            dtype=jnp.float32)             # (G, Sg, E)
        # Static position assignment: the k-th token of a group routed to an
        # expert takes slot k-1; slots >= cap overflow (dropped — residual
        # carries the token). cumsum replaces a sort: order is arrival order.
        pos = (jnp.cumsum(oh, axis=1) * oh).sum(-1) - 1.0  # (G, Sg)
        slot = jnp.where(pos < cap, pos, cap).astype(jnp.int32)
        slot_oh = jax.nn.one_hot(slot, cap + 1,
                                 dtype=jnp.float32)[..., :cap]  # (G, Sg, C)
        dispatch = oh[..., None] * slot_oh[..., None, :]   # (G, Sg, E, C)

        # Gather per-expert token blocks; e shards over ep, so each shard
        # builds + runs only its experts' (G, C, D) blocks on the MXU.
        de = dispatch.astype(self.dtype)
        xe = jnp.einsum("gsec,gsd->gecd", de, xg.astype(self.dtype))
        h = nn.gelu(jnp.einsum("gecd,edh->gech", xe, up.astype(self.dtype)))
        oe = jnp.einsum("gech,ehd->gecd", h, down.astype(self.dtype))
        combine = dispatch * top_gate.reshape(g, sg)[..., None, None]
        y = jnp.einsum("gsec,gecd->gsd", combine, oe.astype(jnp.float32))
        return y.reshape(b, s, d)


class MoEBlock(nn.Module):
    dim: int
    heads: int
    num_experts: int
    attn_fn: Callable
    dispatch: str = "dense"
    capacity_factor: float = 1.25
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        from .seqformer import SeqAttention
        x = x + SeqAttention(self.dim, self.heads, self.attn_fn,
                             dtype=self.dtype, name="attn")(nn.LayerNorm()(x))
        h, top = MoEFFN(self.dim, self.num_experts, dispatch=self.dispatch,
                        capacity_factor=self.capacity_factor,
                        dtype=self.dtype, name="moe")(nn.LayerNorm()(x))
        return x + h, top


class MoEClassifier(nn.Module):
    """(B, S, input_dim) float features — or, with ``vocab_size`` set,
    (B, S) integer token ids embedded on-device — → (B, num_classes) with
    MoE FFNs. Token mode is the production wire (2 bytes/token), same
    contract as the seqformer family."""

    seq_len: int
    input_dim: int
    dim: int = 128
    depth: int = 2
    heads: int = 8
    num_experts: int = 8
    num_classes: int = 16
    attn_fn: Callable = None
    dispatch: str = "dense"
    capacity_factor: float = 1.25
    dtype: jnp.dtype = jnp.bfloat16
    vocab_size: int | None = None

    @nn.compact
    def __call__(self, x):
        from ..parallel.ring_attention import reference_attention
        attn_fn = self.attn_fn or reference_attention
        if self.vocab_size is not None:
            h = nn.Embed(self.vocab_size, self.dim, dtype=self.dtype,
                         name="embed")(x)
        else:
            h = nn.Dense(self.dim, dtype=self.dtype, name="embed")(x)
        pos = self.param("pos_emb", nn.initializers.normal(0.02),
                         (1, self.seq_len, self.dim))
        h = h + pos.astype(self.dtype)
        for i in range(self.depth):
            h, _ = MoEBlock(self.dim, self.heads, self.num_experts, attn_fn,
                            dispatch=self.dispatch,
                            capacity_factor=self.capacity_factor,
                            dtype=self.dtype, name=f"block{i}")(h)
        h = nn.LayerNorm()(h.mean(axis=1))
        return nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(h)


def create_moe(rng=None, seq_len: int = 1024, input_dim: int = 64,
               dim: int = 128, depth: int = 2, heads: int = 8,
               num_experts: int = 8, num_classes: int = 16, mesh=None,
               attention: str = "flash", dispatch: str = "dense",
               capacity_factor: float = 1.25, vocab_size: int | None = None):
    """Build model + params; on a mesh with ep > 1 the expert tensors are
    placed with ``MOE_EP_RULES`` so serving/training shard the expert dim.

    ``num_experts`` must divide by the mesh's ep size (static SPMD shapes).
    ``dispatch``: "dense" or "capacity" (see ``MoEFFN``). ``vocab_size``
    switches the input contract to (B, S) token ids.
    """
    from .seqformer import attention_for

    if mesh is not None:
        ep = mesh.shape.get("ep", 1)
        if num_experts % max(ep, 1):
            raise ValueError(
                f"num_experts {num_experts} not divisible by ep={ep}")
    if dispatch not in ("dense", "capacity"):
        raise ValueError(f"unknown dispatch {dispatch!r}")
    model = MoEClassifier(
        seq_len=seq_len, input_dim=input_dim, dim=dim, depth=depth,
        heads=heads, num_experts=num_experts, num_classes=num_classes,
        attn_fn=attention_for(mesh, attention), dispatch=dispatch,
        capacity_factor=capacity_factor, vocab_size=vocab_size)
    init_model = model.clone(attn_fn=lambda q, k, v: q)
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    init_x = (np.zeros((1, seq_len), np.int32) if vocab_size is not None
              else np.zeros((1, seq_len, input_dim), np.float32))
    params = init_model.init(rng, init_x)
    if mesh is not None and mesh.shape.get("ep", 1) > 1:
        from ..parallel.sharding import shard_params
        params = shard_params(params, mesh, MOE_EP_RULES)
    return model, params
