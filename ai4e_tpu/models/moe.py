"""Mixture-of-Experts sequence classifier — the expert-parallel (``ep``)
model family.

The reference has no MoE (or any model internals — containers are opaque);
this family exists so the mesh's ``ep`` axis (``parallel/sharding.py`` AXES)
is exercised by a real servable, the same way seqformer exercises ``sp``.

Design (TPU-first):

- **Routing** is top-1 token-choice, computed as a dense one-hot combine —
  every expert runs over every token and the gate zeroes the losers. That is
  E× the FLOPs of capacity-based dispatch, but it is fully static (no
  data-dependent shapes, no token dropping, bitwise deterministic), which is
  what XLA wants; at serving-size expert counts (4-16) the MXU is still the
  bottleneck and the win is sharding, not sparsity.
- **Expert parallelism**: expert weight tensors are (E, D, H) with
  ``P("ep", None, None)`` — each ep shard holds E/ep experts and computes
  only their einsum slices; the token-combine contraction reduces over E, so
  XLA inserts one ``psum`` over ``ep`` per MoE layer (ICI traffic: one (B,
  S, D) activation — the standard MoE all-reduce pattern).
- Everything else (attention, norms) replicates over ``ep``, so the family
  composes with dp/fsdp/tp exactly like the dense families.
"""

from __future__ import annotations

from typing import Callable

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

# Param-path rules for shard_params: expert-major tensors over ep.
MOE_EP_RULES = {
    "moe/up": P("ep", None, None),
    "moe/down": P("ep", None, None),
}


class MoEFFN(nn.Module):
    dim: int
    num_experts: int
    mlp_ratio: int = 4
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):  # (B, S, D)
        hidden = self.dim * self.mlp_ratio
        # Router in float32: gate ordering must not wobble with bf16 noise.
        logits = nn.Dense(self.num_experts, dtype=jnp.float32,
                          name="router")(x.astype(jnp.float32))
        gates = jax.nn.softmax(logits, axis=-1)            # (B, S, E)
        top = jnp.argmax(gates, axis=-1)                   # (B, S)
        dispatch = (jax.nn.one_hot(top, self.num_experts, dtype=jnp.float32)
                    * jnp.max(gates, axis=-1, keepdims=True))

        up = self.param("up", nn.initializers.lecun_normal(),
                        (self.num_experts, self.dim, hidden))
        down = self.param("down", nn.initializers.lecun_normal(),
                          (self.num_experts, hidden, self.dim))
        xb = x.astype(self.dtype)
        # e is sharded over ep: each shard computes its experts' slices...
        h = jnp.einsum("bsd,edh->bseh", xb, up.astype(self.dtype))
        h = nn.gelu(h)
        out = jnp.einsum("bseh,ehd->bsed", h, down.astype(self.dtype))
        # ...and this contraction reduces over e → one psum over ep.
        y = jnp.einsum("bsed,bse->bsd", out.astype(jnp.float32), dispatch)
        return y.astype(x.dtype), top


class MoEBlock(nn.Module):
    dim: int
    heads: int
    num_experts: int
    attn_fn: Callable
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        from .seqformer import SeqAttention
        x = x + SeqAttention(self.dim, self.heads, self.attn_fn,
                             dtype=self.dtype, name="attn")(nn.LayerNorm()(x))
        h, top = MoEFFN(self.dim, self.num_experts, dtype=self.dtype,
                        name="moe")(nn.LayerNorm()(x))
        return x + h, top


class MoEClassifier(nn.Module):
    """(B, S, input_dim) → (B, num_classes) with MoE FFNs."""

    seq_len: int
    input_dim: int
    dim: int = 128
    depth: int = 2
    heads: int = 8
    num_experts: int = 8
    num_classes: int = 16
    attn_fn: Callable = None
    dtype: jnp.dtype = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        from ..parallel.ring_attention import reference_attention
        attn_fn = self.attn_fn or reference_attention
        h = nn.Dense(self.dim, dtype=self.dtype, name="embed")(x)
        pos = self.param("pos_emb", nn.initializers.normal(0.02),
                         (1, self.seq_len, self.dim))
        h = h + pos.astype(self.dtype)
        for i in range(self.depth):
            h, _ = MoEBlock(self.dim, self.heads, self.num_experts, attn_fn,
                            dtype=self.dtype, name=f"block{i}")(h)
        h = nn.LayerNorm()(h.mean(axis=1))
        return nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(h)


def create_moe(rng=None, seq_len: int = 1024, input_dim: int = 64,
               dim: int = 128, depth: int = 2, heads: int = 8,
               num_experts: int = 8, num_classes: int = 16, mesh=None,
               attention: str = "flash"):
    """Build model + params; on a mesh with ep > 1 the expert tensors are
    placed with ``MOE_EP_RULES`` so serving/training shard the expert dim.

    ``num_experts`` must divide by the mesh's ep size (static SPMD shapes).
    """
    from .seqformer import attention_for

    if mesh is not None:
        ep = mesh.shape.get("ep", 1)
        if num_experts % max(ep, 1):
            raise ValueError(
                f"num_experts {num_experts} not divisible by ep={ep}")
    model = MoEClassifier(
        seq_len=seq_len, input_dim=input_dim, dim=dim, depth=depth,
        heads=heads, num_experts=num_experts, num_classes=num_classes,
        attn_fn=attention_for(mesh, attention))
    init_model = model.clone(attn_fn=lambda q, k, v: q)
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    params = init_model.init(rng,
                             np.zeros((1, seq_len, input_dim), np.float32))
    if mesh is not None and mesh.shape.get("ep", 1) > 1:
        from ..parallel.sharding import shard_params
        params = shard_params(params, mesh, MOE_EP_RULES)
    return model, params
