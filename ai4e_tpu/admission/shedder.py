"""Priority load shedder — lowest class refused first, Retry-After computed.

Under pressure the platform used to answer a flat 503 with a hardcoded
``Retry-After: "2"`` regardless of who asked or how deep the backlog was
(``gateway/router.py``). This shedder makes refusal a POLICY:

- each priority class may occupy only a FRACTION of the capacity —
  interactive traffic can fill it, default stops at 85%, background at
  60% — so as occupancy climbs the classes shed strictly lowest-first,
  and a background flood can never 503 interactive traffic out of its
  reserved headroom (the same shape the micro-batcher's
  ``interactive_reserve`` gives device batches, applied at admission);
- the Retry-After on a refusal is the time the EXCESS above the class's
  threshold should take to drain at the observed drain rate — an honest
  hint that scales with the backlog instead of a constant that is wrong
  in both directions.
"""

from __future__ import annotations

from .deadline import BACKGROUND, DEFAULT, INTERACTIVE, drain_retry_after


class PriorityShedder:
    #: Fraction of capacity each class may occupy before it sheds.
    DEFAULT_FRACTIONS = {INTERACTIVE: 1.0, DEFAULT: 0.85, BACKGROUND: 0.6}

    def __init__(self, fractions: dict[int, float] | None = None):
        self.fractions = dict(fractions or self.DEFAULT_FRACTIONS)

    def threshold(self, priority: int, capacity: int) -> float:
        """Occupancy above which ``priority`` sheds. Classes beyond the
        configured map clamp to the nearest configured neighbor —
        priorities are ordered, not enumerated."""
        if priority in self.fractions:
            frac = self.fractions[priority]
        elif priority <= min(self.fractions):
            frac = self.fractions[min(self.fractions)]
        else:
            frac = self.fractions[max(self.fractions)]
        # Every class, however low, may use at least one slot: a pure
        # background workload on an idle platform must still run.
        return max(1.0, frac * capacity)

    def check(self, priority: int, occupancy: int, capacity: int,
              drain_rate: float = 0.0) -> float | None:
        """None to admit; else the Retry-After (seconds) for the refusal.

        ``occupancy``/``capacity`` are whatever the calling surface
        measures — in-flight vs the adaptive limit on the sync proxy,
        created-set depth vs ``max_backlog`` at the async edge."""
        threshold = self.threshold(priority, capacity)
        if occupancy < threshold:
            return None
        return drain_retry_after(occupancy - threshold + 1.0, drain_rate)
