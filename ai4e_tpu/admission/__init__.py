"""Admission control: end-to-end deadline propagation, priority shedding,
and adaptive concurrency (``docs/admission.md``).

Opt-in via ``PlatformConfig(admission=True)`` /
``AI4E_PLATFORM_ADMISSION=1``. Three parts:

- ``deadline``  — the ``X-Deadline-Ms`` / ``X-Priority`` /
  ``X-Shed-Reason`` vocabulary every hop shares, and the ``expired``
  terminal status;
- ``controller`` — the latency-gradient AIMD limiter that continuously
  resizes the gateway sync in-flight cap and each dispatcher's delivery
  fan-out, plus drain-rate-derived ``Retry-After`` and goodput metrics;
- ``shedder``    — lowest-priority-first refusal with computed backoff.
"""

from .controller import (AdmissionController, AdmissionScope, DecayingRate,
                         GradientLimiter)
from .deadline import (BACKGROUND, DEADLINE_AT_HEADER, DEADLINE_MS_HEADER,
                       DEFAULT, INTERACTIVE, PRIORITY_CLASSES,
                       PRIORITY_HEADER, SHED_REASON_HEADER, DeadlineExceeded,
                       expired, expired_status, parse_deadline_at,
                       parse_priority, priority_name, propagation_headers,
                       remaining_s, shed_reason, worker_admission_kwargs)
from .shedder import PriorityShedder

__all__ = [
    "AdmissionController", "AdmissionScope", "DecayingRate",
    "GradientLimiter", "PriorityShedder", "DeadlineExceeded",
    "DEADLINE_AT_HEADER", "DEADLINE_MS_HEADER", "PRIORITY_HEADER",
    "SHED_REASON_HEADER", "PRIORITY_CLASSES", "INTERACTIVE", "DEFAULT",
    "BACKGROUND", "expired", "expired_status", "parse_deadline_at",
    "parse_priority", "priority_name", "propagation_headers", "remaining_s",
    "shed_reason", "worker_admission_kwargs",
]
