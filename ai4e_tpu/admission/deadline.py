"""Deadline & priority propagation — the per-request admission state.

The reference platform carries a task through the broker and onto the
backend no matter how long it has queued (``BackendQueueProcessor.cs:27-81``
retries for up to 24 h); nothing ever asks whether the client is still
waiting. Under saturation that inverts the metric that matters — goodput
(within-deadline completions/s) — because the device spends its cycles on
work whose caller already gave up (PAPERS.md: *Adaptive Orchestration for
Large-Scale Inference*, *Evaluating Kubernetes Performance for GenAI
Inference*).

This module is the shared vocabulary every hop uses. Pure stdlib — it is
imported by the gateway, broker, batcher, worker, client, and bench, none
of which may drag the others in.

Headers:

- ``X-Deadline-Ms`` (public): the caller's RELATIVE latency budget in
  milliseconds. The gateway anchors it to an absolute wall-clock deadline
  the moment the request is admitted.
- ``X-Deadline-At`` (internal, hop-to-hop): the ABSOLUTE deadline as unix
  seconds. Forwarded by the dispatcher/sync proxy so transport delay can
  never re-extend a budget the way re-anchoring a relative value would.
- ``X-Priority``: ``interactive`` | ``default`` | ``background`` (or the
  numeric class). Unlabeled public requests are ``default``.
- ``X-Shed-Reason`` (response): provenance on every refusal — which hop
  shed the request and why (``deadline``/``pressure``).

Priority classes map directly onto the micro-batcher's integer priorities
(0 = interactive fills batches first; higher classes age toward the front
one class per ``priority_aging_s`` so nothing starves — ``runtime/
batcher.py``): interactive=0, default=1, background=2. The batch API's
stacks already submit at 1, so labeled interactive traffic batches ahead
of stacks and background batches behind them with no extra wiring.
"""

from __future__ import annotations

import time

# Public request header: relative budget, milliseconds.
DEADLINE_MS_HEADER = "X-Deadline-Ms"
# Internal hop-to-hop header: absolute deadline, unix seconds (float).
DEADLINE_AT_HEADER = "X-Deadline-At"
PRIORITY_HEADER = "X-Priority"
SHED_REASON_HEADER = "X-Shed-Reason"

INTERACTIVE = 0
DEFAULT = 1
BACKGROUND = 2

PRIORITY_CLASSES = {
    "interactive": INTERACTIVE,
    "default": DEFAULT,
    "background": BACKGROUND,
}
_PRIORITY_NAMES = {v: k for k, v in PRIORITY_CLASSES.items()}


class DeadlineExceeded(RuntimeError):
    """Raised inside the serving path when work expires before execution
    (the micro-batcher sets it on a pending future at batch-cut time)."""

    def __init__(self, hop: str, deadline_at: float = 0.0):
        super().__init__(f"deadline exceeded at {hop}")
        self.hop = hop
        self.deadline_at = deadline_at


def priority_name(priority: int) -> str:
    """Label for metrics/provenance; out-of-range classes clamp to the
    nearest named one (priorities are ordered, not enumerated)."""
    if priority <= INTERACTIVE:
        return "interactive"
    if priority >= BACKGROUND:
        return "background"
    return _PRIORITY_NAMES.get(priority, "default")


def parse_priority(headers, default: int = DEFAULT) -> int:
    """``X-Priority`` as an integer class. Accepts the class names or a
    bare integer; anything unparseable (attacker-chosen header) falls back
    to ``default`` — a malformed label must never 400 a request that would
    otherwise serve."""
    raw = headers.get(PRIORITY_HEADER)
    if raw is None:
        return default
    value = raw.strip().lower()
    if value in PRIORITY_CLASSES:
        return PRIORITY_CLASSES[value]
    try:
        return max(INTERACTIVE, min(BACKGROUND, int(value)))
    except ValueError:
        return default


def parse_deadline_at(headers, now: float | None = None) -> float:
    """The request's absolute deadline (unix seconds), 0.0 when none.

    ``X-Deadline-At`` (absolute, stamped by an upstream hop) wins over
    ``X-Deadline-Ms`` (relative, anchored HERE at ``now``) — re-anchoring
    a relative budget at every hop would silently extend it by the
    transport time the deadline exists to bound. Malformed or
    non-positive values mean "no deadline" rather than an error."""
    raw = headers.get(DEADLINE_AT_HEADER)
    if raw is not None:
        try:
            at = float(raw)
        except ValueError:
            at = 0.0
        return at if at > 0 else 0.0
    raw = headers.get(DEADLINE_MS_HEADER)
    if raw is None:
        return 0.0
    try:
        budget_ms = float(raw)
    except ValueError:
        return 0.0
    if budget_ms <= 0:
        return 0.0
    return (time.time() if now is None else now) + budget_ms / 1000.0


def expired(deadline_at: float, now: float | None = None) -> bool:
    """True when the deadline exists and has passed."""
    if not deadline_at:
        return False
    return (time.time() if now is None else now) >= deadline_at


def remaining_s(deadline_at: float, now: float | None = None) -> float:
    """Seconds of budget left (may be negative); +inf when no deadline."""
    if not deadline_at:
        return float("inf")
    return deadline_at - (time.time() if now is None else now)


def drain_retry_after(excess: float, drain_rate: float) -> float:
    """THE Retry-After policy, shared by every refusal surface (shedder
    429/503s, the standby 503, deadline-infeasibility sheds): seconds for
    ``excess`` backlog units to drain at the observed rate, clamped to
    [1, 60] — a cold estimator (no drain evidence yet) answers the
    pre-admission constant 2 s rather than infinity, and a hot one never
    tells clients to hammer. One definition, so shed responses and
    standby responses can never advertise different backoff policies."""
    if drain_rate <= 1e-9:
        return 2.0
    return max(1.0, min(60.0, excess / drain_rate))


def expired_status(hop: str) -> str:
    """The terminal Status prose for work shed on deadline at ``hop``.
    Buckets to the ``expired`` canonical state (``TaskStatus.canonical``),
    which is TERMINAL — pollers wake, retention evicts, the client's
    ``wait()`` raises ``TaskExpired``."""
    return f"expired - deadline exceeded at {hop}"


def shed_reason(hop: str, why: str) -> str:
    """``X-Shed-Reason`` provenance value: which hop refused, and why
    (``deadline`` — the budget is already spent; ``pressure`` — the
    shedder refused the class to protect higher-priority work)."""
    return f"{why} at {hop}"


def propagation_headers(deadline_at: float, priority: int) -> dict:
    """Headers a hop attaches when handing admitted work downstream (the
    dispatcher's backend POST, the gateway's sync proxy): the ABSOLUTE
    deadline plus the priority class. The class is ALWAYS explicit — the
    worker's no-header default is interactive (pre-admission behavior for
    direct callers), so omitting `default` here would silently promote
    every default-class request back to interactive at the next hop."""
    headers = {PRIORITY_HEADER: str(priority)}
    if deadline_at:
        headers[DEADLINE_AT_HEADER] = repr(deadline_at)
    return headers


def worker_admission_kwargs(headers) -> dict:
    """Request-side extraction for the worker's endpoint handlers:
    ``{"deadline_at": float, "priority": int}``. The default priority here
    is INTERACTIVE (0), not the public default class — an unlabeled direct
    request to a worker behaves exactly as before this subsystem existed;
    only traffic the gateway classified carries a different class."""
    return {"deadline_at": parse_deadline_at(headers),
            "priority": parse_priority(headers, default=INTERACTIVE)}
