"""Adaptive concurrency control — the limit that replaces fixed constants.

The reference's only overload story is a static per-endpoint thread cap with
503 backpressure (``ai4e_service.py:116-133``); our port reproduced that
shape with fixed knobs (``submit_concurrency=64``, a hand-picked
``dispatcher_concurrency``, an unbounded gateway sync proxy). A static cap
is wrong in both directions: too low and the device idles under headroom,
too high and queueing delay eats every deadline the moment latency shifts
(a checkpoint reload, a degraded tunnel, a noisy neighbor).

``GradientLimiter`` is a latency-gradient AIMD limiter (the
Netflix-concurrency-limits / TCP-Vegas family): it tracks the observed
minimum RTT as the no-load baseline, compares the recent sample RTT
against it, and resizes the limit —

- sample ≈ baseline (headroom): additive increase, ``+≈√limit`` per
  update, so probing is gentle at small limits and meaningful at large;
- sample ≫ baseline (queueing): multiplicative decrease proportional to
  the gradient ``baseline·tolerance / sample``;
- Little's-law clamp: the limit never grows past twice the concurrency
  actually observed in flight — an idle scope cannot ratchet its cap to
  the maximum and then dump a latency cliff on the first burst.

``AdmissionController`` owns one limiter per SCOPE (the gateway's sync
proxy; each dispatcher queue), applies limit changes to registered targets
(``Gateway`` sync cap, ``Dispatcher.set_concurrency``), estimates the
platform's drain rate from the task store's terminal transitions (the
``Retry-After`` every shed response carries — computed, not hardcoded),
and exports the ``ai4e_admission_*`` metric family including goodput.
"""

from __future__ import annotations

import logging
import math
import threading
import time

from ..metrics import DEFAULT_REGISTRY, MetricsRegistry
from .deadline import drain_retry_after, priority_name, remaining_s
from .shedder import PriorityShedder

log = logging.getLogger("ai4e_tpu.admission")


class DecayingRate:
    """Exponentially decayed event rate (events/second).

    ``on_event`` folds ``n`` events in with time-decay ``tau``; at a steady
    arrival rate r the estimate converges to r. Cheap (O(1), no buckets)
    and thread-safe — terminal transitions arrive from whatever thread ran
    the store upsert."""

    def __init__(self, tau_s: float = 10.0):
        self.tau = tau_s
        self._rate = 0.0
        self._t: float | None = None
        self._lock = threading.Lock()

    def on_event(self, n: float = 1.0, now: float | None = None) -> None:
        now = time.monotonic() if now is None else now
        with self._lock:
            if self._t is not None:
                self._rate *= math.exp(-(now - self._t) / self.tau)
            self._t = now
            self._rate += n / self.tau

    def rate(self, now: float | None = None) -> float:
        now = time.monotonic() if now is None else now
        with self._lock:
            if self._t is None:
                return 0.0
            return self._rate * math.exp(-(now - self._t) / self.tau)


class GradientLimiter:
    """Latency-gradient AIMD concurrency limit (see module docstring).

    Updates are sample-window driven (every ``window`` observations), so
    tests can drive convergence deterministically and a dead-quiet scope
    simply keeps its last limit — no background task, no timers."""

    def __init__(self, initial: int = 8, min_limit: int = 1,
                 max_limit: int = 256, window: int = 16,
                 tolerance: float = 2.0, smoothing: float = 0.3):
        if not (0 < min_limit <= initial <= max_limit):
            raise ValueError(
                f"need min <= initial <= max, got {min_limit}/{initial}/"
                f"{max_limit}")
        self.min_limit = min_limit
        self.max_limit = max_limit
        self.window = max(1, window)
        self.tolerance = tolerance
        self.smoothing = smoothing
        self._limit = float(initial)
        self._samples: list[float] = []
        self._peak_inflight = 0
        # No-load RTT baseline: smallest sample seen, aged ~2%/update so a
        # permanent regime change (new model, new link) can re-learn rather
        # than comparing against a baseline no request will ever hit again.
        self._min_rtt: float | None = None

    @property
    def limit(self) -> int:
        return max(self.min_limit, int(self._limit))

    def observe(self, rtt_s: float, inflight: int) -> bool:
        """Record one completed request's RTT at ``inflight`` concurrency.
        Returns True when the limit value changed (callers re-apply targets
        only then)."""
        if rtt_s < 0:
            return False
        self._samples.append(rtt_s)
        self._peak_inflight = max(self._peak_inflight, inflight)
        if len(self._samples) < self.window:
            return False
        return self._update()

    def backoff(self, factor: float = 0.8) -> bool:
        """Out-of-band multiplicative decrease — explicit backpressure
        (429/503 from a backend) is a stronger signal than latency and
        must not wait out a sample window."""
        before = self.limit
        self._limit = max(float(self.min_limit), self._limit * factor)
        return self.limit != before

    def _update(self) -> bool:
        samples = sorted(self._samples)
        self._samples.clear()
        peak, self._peak_inflight = self._peak_inflight, 0
        sample_rtt = samples[len(samples) // 2]  # median: spike-robust
        if self._min_rtt is None:
            self._min_rtt = sample_rtt
        else:
            self._min_rtt = min(self._min_rtt * 1.02, sample_rtt)
        before = self.limit
        allowance = math.sqrt(self._limit)
        target = self._min_rtt * self.tolerance
        if sample_rtt <= target or sample_rtt <= 0:
            # Headroom: additive increase.
            new = self._limit + allowance
        else:
            # Queueing: shrink toward gradient × limit (multiplicative),
            # keeping the queue allowance so the limit can re-probe.
            gradient = max(0.25, target / sample_rtt)
            new = self._limit * gradient + allowance
        # Little's-law clamp: concurrency beyond what the offered load
        # actually uses is pure latency headroom for the next burst to
        # burn — cap growth at 2× the observed in-flight peak.
        if peak > 0:
            new = min(new, 2.0 * peak + allowance)
        self._limit = min(float(self.max_limit),
                          max(float(self.min_limit),
                              (1 - self.smoothing) * self._limit
                              + self.smoothing * new))
        return self.limit != before


class AdmissionScope:
    """One limited surface (the gateway sync proxy, one dispatcher queue):
    a limiter + its in-flight count + the targets its limit drives."""

    def __init__(self, name: str, controller: "AdmissionController",
                 limiter: GradientLimiter):
        self.name = name
        self._controller = controller
        self.limiter = limiter
        self.inflight = 0
        self._targets: list = []

    @property
    def limit(self) -> int:
        return self.limiter.limit

    def add_target(self, apply_fn) -> None:
        """``apply_fn(limit)`` is invoked on every limit change (and once
        at registration, so a target never runs at a stale constant)."""
        self._targets.append(apply_fn)
        self._apply(apply_fn)

    def try_acquire(self, priority: int) -> float | None:
        """Admit one request at ``priority``: None, and the caller MUST
        ``release()``; or the computed Retry-After seconds when the
        shedder refuses the class at the current occupancy."""
        retry_after = self._controller.shedder.check(
            priority, self.inflight, self.limit,
            drain_rate=self._controller.drain_rate())
        if retry_after is not None:
            return retry_after
        self.inflight += 1
        return None

    def release(self) -> None:
        self.inflight = max(0, self.inflight - 1)

    def observe(self, rtt_s: float, inflight: int | None = None) -> None:
        changed = self.limiter.observe(
            rtt_s, self.inflight if inflight is None else inflight)
        if changed:
            self._apply_all()
        self._controller._limit_gauge.set(self.limit, scope=self.name)

    def backoff(self) -> None:
        if self.limiter.backoff():
            self._apply_all()
            self._controller._limit_gauge.set(self.limit, scope=self.name)

    def _apply_all(self) -> None:
        for fn in self._targets:
            self._apply(fn)

    def _apply(self, fn) -> None:
        try:
            fn(self.limit)
        except Exception:  # noqa: BLE001 — a target must not kill admission
            log.exception("admission target for scope %s failed", self.name)


class AdmissionController:
    """The platform's admission brain (one per assembly, opt-in via
    ``PlatformConfig(admission=True)``)."""

    # Scope names the assembly wires (public so tests/docs agree).
    SYNC_SCOPE = "gateway_sync"

    def __init__(self, metrics: MetricsRegistry | None = None,
                 min_limit: int = 1, max_limit: int = 256,
                 initial_limit: int = 8, max_backlog: int = 1024,
                 shedder: PriorityShedder | None = None,
                 drain_tau_s: float = 10.0):
        self.metrics = metrics or DEFAULT_REGISTRY
        if not (0 < min_limit <= initial_limit <= max_limit):
            # Scopes are created lazily (first request); an inconsistent
            # triple must fail HERE, at assembly, not as a 500 inside the
            # first sync handler that touches the limiter.
            raise ValueError(
                f"admission limits need 0 < min <= initial <= max, got "
                f"min={min_limit} initial={initial_limit} max={max_limit}")
        self.min_limit = min_limit
        self.max_limit = max_limit
        self.initial_limit = initial_limit
        self.max_backlog = max_backlog
        self.shedder = shedder or PriorityShedder()
        self._scopes: dict[str, AdmissionScope] = {}
        self._drain = DecayingRate(tau_s=drain_tau_s)
        self._arrivals = DecayingRate(tau_s=drain_tau_s)
        self._tau_s = drain_tau_s
        # Per-route arrival/drain estimators (keyed by endpoint path,
        # populated lazily by the store listener): the predictive
        # autoscaler scales ONE route's dispatchers, so it must read
        # THAT route's imbalance — the platform-global rates above would
        # attribute a flooded route's growth to every idle route's
        # scaler (bounded: one pair per registered endpoint).
        self._route_arrivals: dict[str, DecayingRate] = {}
        self._route_drains: dict[str, DecayingRate] = {}
        # Degradation ladder (orchestration/ladder.py); None → no brownout
        # modes, the pre-orchestration shedder behavior untouched. Set via
        # set_ladder (the platform assembly wires it) and consulted
        # FIRST on every admission decision — a declared brownout
        # outranks per-request occupancy math.
        self._ladder = None
        self._shed_total = self.metrics.counter(
            "ai4e_admission_shed_total",
            "Requests refused under pressure, by hop/priority")
        self._expired_total = self.metrics.counter(
            "ai4e_admission_expired_total",
            "Requests dropped on deadline expiry, by hop/priority")
        self._limit_gauge = self.metrics.gauge(
            "ai4e_admission_limit", "Current adaptive concurrency limit")
        self._goodput_total = self.metrics.counter(
            "ai4e_admission_goodput_total",
            "Terminal completions by deadline outcome")
        self._drain_gauge = self.metrics.gauge(
            "ai4e_admission_drain_rate",
            "Estimated terminal transitions per second")
        self._arrival_gauge = self.metrics.gauge(
            "ai4e_admission_arrival_rate",
            "Estimated task creations per second (predictive-scaling "
            "numerator beside the drain rate)")

    # -- scopes ------------------------------------------------------------

    def scope(self, name: str) -> AdmissionScope:
        sc = self._scopes.get(name)
        if sc is None:
            sc = self._scopes[name] = AdmissionScope(
                name, self,
                GradientLimiter(initial=self.initial_limit,
                                min_limit=self.min_limit,
                                max_limit=self.max_limit))
            self._limit_gauge.set(sc.limit, scope=name)
        return sc

    def add_target(self, scope_name: str, apply_fn) -> None:
        self.scope(scope_name).add_target(apply_fn)

    # -- shed/expiry accounting (every hop funnels through these) ----------

    def note_shed(self, hop: str, priority: int) -> None:
        self._shed_total.inc(hop=hop, priority=priority_name(priority))

    def note_expired(self, hop: str, priority: int) -> None:
        self._expired_total.inc(hop=hop, priority=priority_name(priority))

    # -- drain rate / Retry-After ------------------------------------------

    def on_drain_event(self, n: float = 1.0) -> None:
        self._drain.on_event(n)

    def drain_rate(self) -> float:
        rate = self._drain.rate()
        self._drain_gauge.set(rate)
        return rate

    def retry_after_s(self, excess: float = 1.0) -> float:
        """Seconds until roughly ``excess`` units of backlog should have
        drained — the Retry-After on shed/standby responses (the shared
        ``drain_retry_after`` policy)."""
        return drain_retry_after(excess, self.drain_rate())

    def arrival_rate(self, route: str | None = None) -> float:
        """Decayed task-creation rate — paired with ``drain_rate`` this is
        the queue-growth projection the predictive autoscaler acts on
        (``scaling.predictive_signal``). ``route`` (an endpoint path)
        narrows to that route's own estimator; None is the platform-wide
        rate (and updates the gauge)."""
        if route is not None:
            est = self._route_arrivals.get(route)
            return est.rate() if est is not None else 0.0
        rate = self._arrivals.rate()
        self._arrival_gauge.set(rate)
        return rate

    def route_drain_rate(self, route: str) -> float:
        """One route's decayed terminal-transition rate (the per-route
        counterpart of ``drain_rate``, which stays platform-wide — it
        feeds Retry-After, a whole-platform statement)."""
        est = self._route_drains.get(route)
        return est.rate() if est is not None else 0.0

    def _route_rate(self, table: dict, route: str) -> DecayingRate:
        est = table.get(route)
        if est is None:
            est = table[route] = DecayingRate(tau_s=self._tau_s)
        return est

    # -- degradation ladder (orchestration) --------------------------------

    def set_ladder(self, ladder) -> None:
        """Attach (or clear with None) the degradation ladder: admission
        decisions consult it first, and the store listener feeds it
        actual deadline outcomes (docs/orchestration.md)."""
        self._ladder = ladder

    def brownout_refusal(self, priority: int) -> tuple[float, str] | None:
        """``(retry_after_s, mode)`` when the ladder refuses this class
        right now, else None. The sync proxy calls this beside
        ``try_acquire``; the async edge gets the same consult inside
        ``shed_async``."""
        if self._ladder is None:
            return None
        mode = self._ladder.refuse(priority)
        if mode is None:
            return None
        return self.retry_after_s(), mode

    # -- async-edge admission ----------------------------------------------

    def shed_async(self, priority: int, backlog: int,
                   deadline_at: float = 0.0
                   ) -> tuple[float, str] | None:
        """Edge decision for the async task-creation path: None to admit,
        else ``(retry_after_s, why)``.

        Three tests, cheapest first:
        - brownout — a declared ladder mode refusing this class outranks
          any per-request math (the ladder already saw sustained
          predicted-miss pressure);
        - class pressure — the backlog (created-set depth for the route)
          against this class's share of ``max_backlog``, lowest priority
          refused first (the shedder's fractions);
        - deadline feasibility — with a deadline and an established drain
          rate, a predicted queue wait beyond the remaining budget means
          the task would expire in the queue; refusing NOW costs the
          client one cheap 429 instead of a full transport round trip
          ending in an expired record."""
        brown = self.brownout_refusal(priority)
        if brown is not None:
            return brown[0], "brownout"
        retry_after = self.shedder.check(priority, backlog, self.max_backlog,
                                         drain_rate=self.drain_rate())
        if retry_after is not None:
            return retry_after, "pressure"
        if deadline_at and backlog >= 8:
            rate = self.drain_rate()
            if rate > 1e-9 and backlog / rate > remaining_s(deadline_at):
                return self.retry_after_s(), "deadline"
        return None

    # -- goodput wiring -----------------------------------------------------

    def attach_store(self, store) -> None:
        """Subscribe to the task store's change feed (the same feed the
        gateway's long-poll waiters and the result cache ride): every
        terminal transition is a drain event for the Retry-After
        estimator, and completed tasks score goodput by whether they beat
        their deadline (``no_deadline`` kept separate so the ratio stays
        meaningful for deadline-carrying traffic)."""
        from ..taskstore import TaskStatus, endpoint_path

        def on_task_change(task) -> None:
            status = task.canonical_status
            if status not in TaskStatus.TERMINAL:
                if task.status == TaskStatus.CREATED:
                    # The RAW "created" status is stamped exactly once, at
                    # creation (requeues/backpressure rewrites carry
                    # provenance prose) — the arrival-rate event for the
                    # predictive scaler, platform-wide and per route. The
                    # gauge updates HERE: production readers use the
                    # per-route form of arrival_rate, which must not be
                    # the only thing keeping the platform-wide gauge live.
                    self._arrivals.on_event()
                    self._arrival_gauge.set(self._arrivals.rate())
                    self._route_rate(self._route_arrivals,
                                     endpoint_path(task.endpoint)).on_event()
                return
            self.on_drain_event()
            self._route_rate(self._route_drains,
                             endpoint_path(task.endpoint)).on_event()
            deadline_at = getattr(task, "deadline_at", 0.0)
            if status != TaskStatus.COMPLETED:
                if (self._ladder is not None and deadline_at
                        and status == TaskStatus.EXPIRED):
                    # Shed on its deadline somewhere downstream — actual
                    # miss evidence for the brownout ladder.
                    self._ladder.note(miss=True)
                return
            if not deadline_at:
                outcome = "no_deadline"
            elif time.time() <= deadline_at:
                outcome = "in_deadline"
            else:
                outcome = "late"
            self._goodput_total.inc(outcome=outcome)
            if self._ladder is not None and deadline_at:
                self._ladder.note(miss=(outcome == "late"))

        store.add_listener(on_task_change)
