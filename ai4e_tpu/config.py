"""Typed configuration with environment-variable overrides.

The reference configures everything through two untyped tiers — bash variables
in ``InfrastructureDeployment/setup_env.sh:1-82`` at deploy time, and raw
``getenv`` reads scattered through the code at runtime
(``APIs/1.0/base-py/ai4e_service.py:19-22``, ``APIs/1.0/Common/task_management/
distributed_api_task.py:14-15``, ``ProcessManager/Libraries/RedisConnection.cs:24-27``)
— with secrets pasted into Helm values files
(``APIs/Charts/camera-trap/detection-async/prod-values.yaml:41-46``).

Here the same two tiers are typed: dataclass sections with defaults (the
deploy-time tier) and an ``AI4E_<SECTION>_<FIELD>`` environment override for
every field (the runtime tier). Values are parsed per the field's declared
type, so a malformed override fails loudly at startup instead of deep inside a
request. No secret material is ever written by the framework; anything
secret-shaped stays an env var end to end.

Usage::

    cfg = FrameworkConfig.from_env()            # defaults + AI4E_* overrides
    cfg.observability.apply()                   # tracer sampling/export sink
    platform = LocalPlatform(cfg.to_platform_config())
"""

from __future__ import annotations

import dataclasses
import os
import typing
from dataclasses import dataclass, field, fields

_TRUE = frozenset({"1", "true", "yes", "on"})
_FALSE = frozenset({"0", "false", "no", "off", ""})

# Out-of-band AI4E_* namespaces, read directly by the paths that need them
# and never part of the typed config: AI4E_FAULT_* (fault injection, e.g.
# AI4E_FAULT_FETCH_FAIL_NTHS), AI4E_CHAOS_* (chaos-harness seeds,
# tests/test_chaos.py), AI4E_FEED_* (the multihost shard feed's direct
# knobs, e.g. AI4E_FEED_ADVERTISE_IP in parallel/multihost.py — previously
# REJECTED by from_env, so a multihost deployment pinning its feed IP
# could not boot; AIL006 surfaced the drift), AI4E_TASKSTORE_* (the
# journal's durability knobs, e.g. AI4E_TASKSTORE_FSYNC read by
# taskstore/journal.py at store construction — a storage-layer policy any
# journal-bearing process honors, whether or not it builds a typed
# FrameworkConfig), AI4E_RIG_* (the multi-process deployment rig's
# driver-side knobs, e.g. AI4E_RIG_BASE_PORT read by ai4e_tpu/rig/ — rig
# child processes are configured by the resolved topology spec file, not
# env). Single source of truth — FrameworkConfig.from_env exempts these
# from its unknown-variable check and the AIL006 config-drift rule
# imports the same tuple. All five are documented in docs/config.md.
OUT_OF_BAND_ENV_PREFIXES = ("AI4E_FAULT_", "AI4E_CHAOS_", "AI4E_FEED_",
                            "AI4E_TASKSTORE_", "AI4E_RIG_")


class ConfigError(ValueError):
    pass


def _parse(raw: str, typ, name: str):
    """Parse an env string per the declared field type."""
    origin = typing.get_origin(typ)
    if origin is typing.Union:  # Optional[X] — "" means None
        args = [a for a in typing.get_args(typ) if a is not type(None)]
        if raw == "":
            return None
        return _parse(raw, args[0], name)
    if typ is bool:
        low = raw.strip().lower()
        if low in _TRUE:
            return True
        if low in _FALSE:
            return False
        raise ConfigError(f"{name}: {raw!r} is not a boolean")
    if typ is int:
        try:
            return int(raw)
        except ValueError as e:
            raise ConfigError(f"{name}: {raw!r} is not an int") from e
    if typ is float:
        try:
            return float(raw)
        except ValueError as e:
            raise ConfigError(f"{name}: {raw!r} is not a float") from e
    if origin in (tuple, list):
        item_t = (typing.get_args(typ) or (str,))[0]
        if item_t is Ellipsis:
            item_t = str
        items = [s.strip() for s in raw.split(",") if s.strip()]
        parsed = [_parse(s, item_t, name) for s in items]
        return tuple(parsed) if origin is tuple else parsed
    return raw


def section_from_env(cls, env: typing.Mapping[str, str] | None = None,
                     prefix: str = "AI4E_", **overrides):
    """Build a config dataclass from defaults + ``{prefix}{FIELD}`` env vars.

    Explicit ``overrides`` win over env, env wins over defaults — the same
    precedence the reference gets from Helm values overriding chart defaults
    (``APIs/Charts/templates/async-gpu/templates/deployment.yaml:23-63``).
    """
    env = os.environ if env is None else env
    kwargs = {}
    hints = typing.get_type_hints(cls)
    known = {prefix + f.name.upper(): f.name for f in fields(cls)}
    for key, name in known.items():
        if name in overrides:
            kwargs[name] = overrides[name]
        elif key in env:
            kwargs[name] = _parse(env[key], hints[name], key)
    # A prefixed-but-unknown variable is a misspelled field, the most common
    # operator error — fail loudly instead of silently keeping the default.
    unknown = [k for k in env if k.startswith(prefix) and k not in known]
    if unknown:
        raise ConfigError(
            f"unknown config variable(s) {sorted(unknown)}; "
            f"valid: {sorted(known)}")
    return cls(**kwargs)


def _env_section(prefix: str):
    """Class decorator: attach ``from_env`` with the section's prefix."""
    def deco(cls):
        cls = dataclass(cls)
        cls._env_prefix = prefix

        def from_env(inner_cls, env=None, **overrides):
            return section_from_env(inner_cls, env=env, prefix=prefix,
                                    **overrides)

        cls.from_env = classmethod(from_env)
        return cls
    return deco


@_env_section("AI4E_PLATFORM_")
class PlatformSection:
    """Transport/task-fabric knobs (setup_env.sh:65-74 tier)."""
    transport: str = "queue"         # TRANSPORT_TYPE (setup_env.sh:11): queue | push
    retry_delay: float = 60.0        # dispatcher backoff on 429/503 (s)
    max_delivery_count: int = 1440   # broker patience (setup_env.sh:65)
    dispatcher_concurrency: int = 1  # serial per queue (host.json:5-9)
    journal_path: typing.Optional[str] = None
    lease_seconds: float = 300.0
    native_broker: bool = False
    native_store: bool = False
    push_ttl_seconds: float = 300.0  # event TTL 5 min (deploy_event_grid_subscription.sh:37)
    push_max_attempts: int = 3       # max delivery attempts (same line)
    push_window: int = 256           # concurrent in-flight deliveries
    # Stuck-task watchdog (taskstore/reaper.py): rescue tasks stuck in
    # "running" after a worker died post-adoption. None disables.
    reaper_running_timeout: typing.Optional[float] = None
    reaper_interval: float = 30.0
    reaper_max_requeues: int = 3
    # Terminal-history retention (s): evict completed/failed tasks older
    # than this — the memory bound a sustained-traffic control plane needs
    # (a 20-min 200 req/s soak grew an unevicted store ~12 MB/min). Unset
    # = AUTO: 15 min on the Python store (bounds that workload's steady
    # state at ~180 MB), off on the native store (which has no eviction).
    # 0 = evict terminal tasks immediately; negative = keep forever.
    reaper_terminal_retention: typing.Optional[float] = None
    # Object-store result offload (assign_storage_auth_to_aks.sh:9-17 slot):
    # results >= threshold bytes land under result_dir instead of store memory.
    result_dir: typing.Optional[str] = None
    result_offload_threshold: int = 1048576
    # Control-plane HA (taskstore/replication.py): primary URL to replicate
    # from — set on the STANDBY replica (requires journal_path); a watchdog
    # promotes it when the primary dies.
    replicate_from: typing.Optional[str] = None
    failover_interval: float = 2.0
    failover_down_after: int = 3
    # Subscription key for the primary's keyed control-plane port (the
    # journal stream rides behind the gateway key middleware).
    replicate_api_key: typing.Optional[str] = None
    # This node's control-plane URL as peers reach it — after a promotion
    # the fencing prober sends it in demote calls so the deposed primary
    # rejoins the new primary automatically (split-brain fencing).
    advertise_url: typing.Optional[str] = None
    # Inference result cache + single-flight coalescing (docs/rescache.md).
    # Off by default: enabling is a semantic statement that identical
    # payloads may share results; per-request opt-out via X-Cache-Bypass.
    result_cache: bool = False
    cache_max_entries: int = 4096
    cache_max_bytes: int = 268435456          # 256 MiB resident payloads
    cache_ttl_seconds: typing.Optional[float] = 300.0
    # Admission control (docs/admission.md): deadline propagation
    # (X-Deadline-Ms/X-Priority), priority shedding with computed
    # Retry-After, adaptive gateway-sync/dispatcher concurrency. Off by
    # default: enabling it means the platform may refuse or expire work
    # (terminal `expired` status) instead of serving arbitrarily late.
    admission: bool = False
    admission_min_limit: int = 1
    admission_max_limit: int = 256
    admission_initial_limit: int = 8
    admission_max_backlog: int = 1024
    # Resilient routing (docs/resilience.md): per-backend circuit breakers
    # shared by the sync proxy and every dispatcher, health-aware weighted
    # picks (open backends ejected), budget-bounded retries with failover
    # on connection error, 5xx treated as transient (redelivered). Off by
    # default: enabling it changes failure semantics — a 5xx is no longer
    # instantly terminal.
    resilience: bool = False
    resilience_failure_threshold: int = 5
    resilience_window: int = 16
    resilience_error_rate: float = 0.5
    resilience_recovery_seconds: float = 30.0
    resilience_max_attempts: int = 3
    resilience_retry_base_s: float = 0.05
    resilience_retry_budget_ratio: float = 0.2
    # Deadline-aware orchestration (docs/orchestration.md): per-request
    # placement across unequal backends on predicted completion-within-
    # deadline, the brownout degradation ladder, and predictive
    # autoscaling. Requires admission AND resilience (it composes their
    # signals).
    orchestration: bool = False
    orchestration_confidence: float = 0.75
    orchestration_window: int = 256
    orchestration_horizon_s: float = 60.0
    # "substring=cost,..." per-backend relative cost (first match wins;
    # unmatched backends cost 1.0).
    orchestration_costs: typing.Optional[str] = None
    orchestration_ladder_up: float = 0.3
    orchestration_ladder_down: float = 0.1
    orchestration_ladder_hold_s: float = 5.0
    orchestration_scale_horizon_s: float = 10.0
    # Sharded task store (docs/sharding.md): N independent shards over a
    # consistent-hash slot ring, each with its own journal, passive
    # replicas, and epoch-fenced failover. 1 = today's single store.
    task_shards: int = 1
    task_shard_slots: int = 64
    task_shard_replicas: int = 1
    shard_tail_interval: float = 0.25
    shard_feed_recent: int = 4096
    # Request observability (docs/observability.md): per-task hop
    # ledger, tail-sampled flight recorder (GET /v1/debug/flight), and
    # per-route e2e latency/outcome telemetry. Off = byte-identical
    # assembly.
    observability: bool = False
    flight_capacity: int = 512
    flight_sample: float = 0.05
    flight_slow_ms: float = 1000.0
    # Per-route SLO objectives + multi-window burn-rate engine
    # (observability/slo.py): "/route=<latency_ms>:<target_pct>" or
    # "/route=goodput:<target_pct>", comma-separated. Requires
    # observability (the engine reads its histograms). Unset = no
    # engine.
    slo_objectives: typing.Optional[str] = None
    slo_tick_s: float = 5.0
    slo_fast_window_s: float = 300.0
    slo_slow_window_s: float = 3600.0
    # Feed sustained SLO breaches to the degradation ladder as an extra
    # miss-evidence source (requires orchestration).
    slo_ladder: bool = False
    # First-class pipeline DAGs (docs/pipelines.md): declared multi-stage
    # compositions executed under one TaskId by the coordinator, plus the
    # SSE streaming surface GET /v1/taskmanagement/task/{id}/events.
    # Requires the Python store/broker + queue transport. Off =
    # byte-identical assembly.
    pipeline: bool = False
    # Per-task event replay buffer for late-attaching streams, and the
    # maximum SSE stream duration per request (seconds).
    pipeline_event_replay: int = 256
    pipeline_stream_max_s: float = 300.0
    # Separate bound for CHUNK events (token streams): a late attacher
    # replays at most this many trailing chunks, older ones are dropped
    # with a single `truncated` marker — a slow client must never hold
    # unbounded token history (docs/streaming.md).
    pipeline_chunk_replay: int = 128

    def to_platform_config(self):
        from .platform_assembly import PlatformConfig
        return PlatformConfig(
            transport=self.transport,
            retry_delay=self.retry_delay,
            max_delivery_count=self.max_delivery_count,
            dispatcher_concurrency=self.dispatcher_concurrency,
            journal_path=self.journal_path,
            lease_seconds=self.lease_seconds,
            native_broker=self.native_broker,
            native_store=self.native_store,
            push_ttl_seconds=self.push_ttl_seconds,
            push_max_attempts=self.push_max_attempts,
            push_window=self.push_window,
            reaper_running_timeout=self.reaper_running_timeout,
            reaper_interval=self.reaper_interval,
            reaper_max_requeues=self.reaper_max_requeues,
            reaper_terminal_retention=self.reaper_terminal_retention,
            result_dir=self.result_dir,
            result_offload_threshold=self.result_offload_threshold,
            replicate_from=self.replicate_from,
            failover_interval=self.failover_interval,
            failover_down_after=self.failover_down_after,
            replicate_api_key=next(
                (k.strip() for k in (self.replicate_api_key or "").split(",")
                 if k.strip()), None),
            advertise_url=self.advertise_url,
            result_cache=self.result_cache,
            cache_max_entries=self.cache_max_entries,
            cache_max_bytes=self.cache_max_bytes,
            cache_ttl_seconds=self.cache_ttl_seconds,
            admission=self.admission,
            admission_min_limit=self.admission_min_limit,
            admission_max_limit=self.admission_max_limit,
            admission_initial_limit=self.admission_initial_limit,
            admission_max_backlog=self.admission_max_backlog,
            resilience=self.resilience,
            resilience_failure_threshold=self.resilience_failure_threshold,
            resilience_window=self.resilience_window,
            resilience_error_rate=self.resilience_error_rate,
            resilience_recovery_seconds=self.resilience_recovery_seconds,
            resilience_max_attempts=self.resilience_max_attempts,
            resilience_retry_base_s=self.resilience_retry_base_s,
            resilience_retry_budget_ratio=self.resilience_retry_budget_ratio,
            orchestration=self.orchestration,
            orchestration_confidence=self.orchestration_confidence,
            orchestration_window=self.orchestration_window,
            orchestration_horizon_s=self.orchestration_horizon_s,
            orchestration_costs=self.orchestration_costs,
            orchestration_ladder_up=self.orchestration_ladder_up,
            orchestration_ladder_down=self.orchestration_ladder_down,
            orchestration_ladder_hold_s=self.orchestration_ladder_hold_s,
            orchestration_scale_horizon_s=self.orchestration_scale_horizon_s,
            task_shards=self.task_shards,
            task_shard_slots=self.task_shard_slots,
            task_shard_replicas=self.task_shard_replicas,
            shard_tail_interval=self.shard_tail_interval,
            shard_feed_recent=self.shard_feed_recent,
            observability=self.observability,
            flight_capacity=self.flight_capacity,
            flight_sample=self.flight_sample,
            flight_slow_ms=self.flight_slow_ms,
            slo_objectives=self.slo_objectives,
            slo_tick_s=self.slo_tick_s,
            slo_fast_window_s=self.slo_fast_window_s,
            slo_slow_window_s=self.slo_slow_window_s,
            slo_ladder=self.slo_ladder,
            pipeline=self.pipeline,
            pipeline_event_replay=self.pipeline_event_replay,
            pipeline_stream_max_s=self.pipeline_stream_max_s,
            pipeline_chunk_replay=self.pipeline_chunk_replay,
        )


@_env_section("AI4E_SERVICE_")
class ServiceSection:
    """In-container service shell knobs (ai4e_service.py:19-22 tier)."""
    host: str = "0.0.0.0"
    port: int = 8081
    executor_workers: int = 8
    drain_timeout: float = 30.0
    # Cross-replica in-flight reporter (REQUEST_REPORTER_URI +
    # SERVICE_CLUSTER in ai4e_service.py:21,135-146); None disables.
    reporter_uri: typing.Optional[str] = None
    cluster: str = "local"
    # Subscription key the worker attaches to task-store calls when the
    # control plane runs with gateway api_keys (same secret).
    taskstore_api_key: typing.Optional[str] = None
    # Direct-to-storage results: large outputs write to this shared mount
    # (the SAME root the control plane serves via AI4E_PLATFORM_RESULT_DIR)
    # and only a pointer registration crosses the control network.
    result_dir: typing.Optional[str] = None
    result_offload_threshold: int = 1048576


@_env_section("AI4E_RUNTIME_")
class RuntimeSection:
    """TPU runtime knobs — no reference analogue (containers were opaque)."""
    platform: typing.Optional[str] = None  # pin jax_platforms (e.g. "cpu")
    batch_max_wait_ms: float = 5.0
    batch_max_pending: int = 256
    # In-flight device batches (MicroBatcher pipeline window). 2 = double
    # buffering, right for a locally-attached chip; raise to ~6 when the
    # host↔device link is long-fat (remote-attached TPU) so transfers of
    # several batches overlap.
    batch_pipeline_depth: int = 2
    # Priority-class batching (batch-API stacks run at background priority):
    # fraction of batch_max_pending reserved for interactive admissions, and
    # the seconds of waiting that promote a background item one class
    # (0 = strict priority).
    batch_interactive_reserve: float = 0.25
    batch_priority_aging_s: float = 2.0
    # Double-buffered device transfers (docs/device_path.md): h2d/execute/
    # d2h on dedicated threads with an alternating staging-buffer ring so
    # batch N+1's device_put overlaps batch N's execute. Off = the fused
    # single-executor path, byte-identical to the pre-double-buffer worker.
    batch_double_buffer: bool = False
    # Traffic-tuned bucket ladders (runtime/ladder.py, docs/device_path.md):
    # derive each servable's batch buckets from the live cut-size histogram,
    # AOT-compile in the background, swap atomically, persist beside the
    # compile cache. Off = static factory ladders, byte-identical batch
    # path and /metrics.
    ladder_derive: bool = False
    ladder_window_s: float = 300.0       # histogram decay half-life
    ladder_max_programs: int = 16        # compiled-programs budget per model
    ladder_period_s: float = 60.0        # re-derive cadence per model
    ladder_dwell_s: float = 120.0        # min seconds between ladder swaps
    # Persisted derived-ladder file; unset = <compile_cache_dir>/ladders.json
    # (beside the persistent compilation cache, so a restart AOT-warms the
    # traffic-tuned ladder).
    ladder_path: typing.Optional[str] = None
    buckets: typing.Tuple[int, ...] = (1, 8, 32, 64)
    # Continuous-batching decode engine (runtime/decode.py,
    # docs/streaming.md): iteration-level scheduling over a KV-cache
    # slot pool with per-token `chunk` streaming. Off = the engine is
    # never constructed — the batch path and /metrics exposition are
    # byte-identical to the decode-less worker.
    decode_enable: bool = False
    decode_max_pending: int = 64       # queued streams before 503
    # Prompt-padding bucket ladder; empty = the factory
    # ladder.DECODE_PROMPT_BUCKETS (the KV length is always appended as
    # the covering top bucket).
    decode_prompt_buckets: typing.Tuple[int, ...] = ()
    # KV-cache slot-pool geometry (runtime/kvcache.py): concurrent
    # decoding sequences per model, and the per-slot cache length
    # (prompt + generated tokens must fit under it).
    kv_slots: int = 8
    kv_max_len: int = 256
    compile_cache_dir: str = "/tmp/ai4e_tpu_xla_cache"
    checkpoint_dir: typing.Optional[str] = None
    donate_batch: bool = False
    # mesh axes; 0 = infer from device count
    dp: int = 0
    fsdp: int = 1
    tp: int = 1
    sp: int = 1
    ep: int = 1
    # Mesh serving plane (runtime/mesh/, docs/mesh_serving.md): the
    # declarative serving-mesh spec — "dp=8", "dp=2,tp=2", optionally
    # ",sp=N" — validated at boot and exposed on GET /v1/models. Empty =
    # mesh serving off (byte-identical worker); mutually exclusive with
    # the low-level dp/fsdp/tp/sp/ep axis knobs above.
    mesh_spec: str = ""
    # Consecutive poisoned batches attributed to one mesh process before
    # the endpoint flips unhealthy (admission answers 500; breakers
    # eject it). One clean batch marks it healthy again.
    mesh_unhealthy_after: int = 3


@_env_section("AI4E_GATEWAY_")
class GatewaySection:
    """Edge router knobs (APIManagement tier). The upsert/get URIs are the
    CACHE_CONNECTOR_UPSERT_URI / _GET_URI pattern (distributed_api_task.py:14-15)."""
    host: str = "0.0.0.0"
    port: int = 8080
    taskstore_upsert_uri: typing.Optional[str] = None
    taskstore_get_uri: typing.Optional[str] = None
    # Comma-separated subscription keys; set → every published API and
    # /v1/taskmanagement call must carry one (Ocp-Apim-Subscription-Key or
    # X-Api-Key header) — the reference's APIM front-door contract.
    api_keys: typing.Optional[str] = None
    # Edge payload cap (bytes) for published APIs: oversized POSTs are
    # refused with 413 before any task/ORIG body is stored. 0 = unlimited.
    max_body_bytes: int = 134217728
    # Separate cap for result uploads on the task-store surface — batch
    # results are routinely larger than request bodies. 0 = unlimited.
    max_result_bytes: int = 1073741824
    # Per-key request-rate throttle on the published surface (the APIM
    # product-throttling slot). 0 disables; burst 0 → 2×rps.
    rate_limit_rps: float = 0.0
    rate_limit_burst: float = 0.0
    # Per-key overrides: "key=rps[:burst],..." (gateway/ratelimit.py).
    rate_limits: typing.Optional[str] = None
    # Per-key request QUOTA (APIM product quota; 403 on exhaustion):
    # default "N[/window_seconds]" (bare N = per hour); empty disables.
    quota: typing.Optional[str] = None
    # Per-key overrides: "key=N[/window_seconds],...".
    quotas: typing.Optional[str] = None


@_env_section("AI4E_OBSERVABILITY_")
class ObservabilitySection:
    """Tracing/metrics knobs (OCAGENT_TRACE_EXPORTER_ENDPOINT analogue,
    prod-values.yaml:29)."""
    trace_enabled: bool = True
    trace_sample_rate: float = 1.0   # App Insights sampled 50 items/s (host.json:5-8)
    trace_export_path: typing.Optional[str] = None  # JSONL span log; None → log only
    # OTLP/HTTP traces URL of a collector (deploy/charts/otel-collector.yaml
    # serves http://ai4e-otel-collector:4318/v1/traces) — the deployable
    # span sink, parity with the reference's Istio→App Insights adapter.
    trace_otlp_endpoint: typing.Optional[str] = None
    queue_depth_interval: float = 30.0      # TaskQueueLogger.cs:19 (30 s)
    process_depth_interval: float = 300.0   # TaskProcessLogger.cs:21 (5 min)
    # Per-process runtime vitals (observability/vitals.py): event-loop
    # lag, GC pauses, RSS/CPU/fd/steal from /proc, exported as
    # ai4e_process_* in the process's own registry. Started by the CLI
    # launchers (control-plane AND worker); rig roles always sample.
    # Off = no sampler task, no series — the launcher is byte-identical.
    vitals: bool = False
    vitals_interval: float = 1.0
    # Worker-side hop-ledger participation (docs/observability.md): the
    # batcher measures device phases (h2d/compile/execute/d2h + overlap
    # ratio) and the worker flushes each request's timeline to the task
    # store — pair with AI4E_PLATFORM_OBSERVABILITY on the control
    # plane for the full cross-process ledger. Off = the pre-ledger
    # worker byte for byte.
    hop_ledger: bool = False

    def apply(self) -> None:
        """Install these settings on the process tracer (components without
        explicit tracer settings follow it live)."""
        from .observability import (FanoutExporter, JsonlExporter,
                                    configure_tracer)
        rate = self.trace_sample_rate if self.trace_enabled else 0.0
        exporters = []
        if self.trace_export_path:
            exporters.append(JsonlExporter(self.trace_export_path))
        if self.trace_otlp_endpoint:
            from .observability.otlp import OtlpHttpExporter
            exporters.append(OtlpHttpExporter(self.trace_otlp_endpoint))
        exporter = None
        if len(exporters) == 1:
            exporter = exporters[0]
        elif exporters:
            exporter = FanoutExporter(exporters)
        if exporter is not None and hasattr(exporter, "close"):
            # Flush buffered spans at process exit (the OTLP exporter holds
            # up to flush_interval of them) — the shutdown-time spans are
            # usually the interesting ones.
            import atexit
            atexit.register(exporter.close)
        configure_tracer(exporter=exporter, sample_rate=rate)


@_env_section("AI4E_TENANCY_")
class TenancySection:
    """Multi-tenancy knobs (tenancy/, docs/tenancy.md) — the analogue of
    the reference's per-product APIM subscription policy (rate + quota per
    product, ``create_async_api_management_api.sh:52-80``), plus the
    scheduler-share weight APIM never had."""
    # Master switch → PlatformConfig.tenancy.
    enabled: bool = False
    # Tenant spec "name=key1|key2[:weight[:rps[:burst]]]" comma-separated
    # (tenancy/registry.py parse_tenants).
    tenants: typing.Optional[str] = None
    # Defaults for omitted spec fields AND the default tenant's own policy
    # (rps 0 = unlimited).
    default_weight: float = 1.0
    default_rps: float = 0.0
    default_burst: float = 0.0
    # Bounded metric-label cardinality: first N declared tenants keep
    # their id, the rest collapse into "other" (AIL013's blessed mapper).
    label_top_n: int = 8
    # Goodput target the per-tenant SLO-burn gauge normalizes against.
    goodput_target: float = 0.99
    # Floor on a lane's DRR credit per ring visit.
    min_quantum: float = 0.05


@_env_section("AI4E_ROLLOUT_")
class RolloutSection:
    """Zero-downtime rollout knobs (rollout/, docs/deployment.md#rollouts):
    the drain budget the worker's drain verb enforces and the canary
    ladder/burn bars the rollout controller promotes against."""
    # Per-worker graceful-drain budget: in-flight device batches, active
    # decode sequences and in-flight reloads get this long to finish
    # before stragglers are force-retired (each redelivers per task).
    drain_timeout_ms: float = 30000.0
    # Canary traffic-share ladder in percent, increasing, ending at 100
    # (rollout/controller.parse_steps).
    canary_steps: str = "25,50,100"
    # Clean fast+slow burn window held at each ladder step before
    # promoting to the next.
    step_hold_s: float = 10.0
    # Burn/breaker sampling period inside a hold.
    guard_tick_s: float = 1.0
    # Burn bars: roll back only when BOTH windows breach (the SLO
    # engine's multi-window page shape, observability/slo.py).
    burn_fast_max: float = 1.0
    burn_slow_max: float = 1.0
    # How long a drain-marked backend stays ejected from placement per
    # X-Draining observation (resilience/health.mark_draining).
    drain_eject_ttl_s: float = 30.0
    # The deploy generation this process serves (registry's
    # ServableModel.generation default for reloads that don't name one).
    generation: int = 0


@dataclass
class FrameworkConfig:
    """The whole platform's config tree."""
    platform: PlatformSection = field(default_factory=PlatformSection)
    service: ServiceSection = field(default_factory=ServiceSection)
    runtime: RuntimeSection = field(default_factory=RuntimeSection)
    gateway: GatewaySection = field(default_factory=GatewaySection)
    observability: ObservabilitySection = field(
        default_factory=ObservabilitySection)
    tenancy: TenancySection = field(default_factory=TenancySection)
    rollout: RolloutSection = field(default_factory=RolloutSection)

    @classmethod
    def from_env(cls, env: typing.Mapping[str, str] | None = None
                 ) -> "FrameworkConfig":
        hints = typing.get_type_hints(cls)
        sections = {f.name: hints[f.name] for f in fields(cls)}
        # Per-section checks only catch misspelled *fields*; a misspelled
        # *section* ("AI4E_OBSERVABILTY_...") matches no section prefix and
        # would silently keep every default — catch it here.
        env_map = os.environ if env is None else env
        prefixes = tuple(s._env_prefix for s in sections.values())
        unknown = [k for k in env_map
                   if k.startswith("AI4E_") and not k.startswith(prefixes)
                   and not k.startswith(OUT_OF_BAND_ENV_PREFIXES)]
        if unknown:
            raise ConfigError(
                f"unknown config section in variable(s) {sorted(unknown)}; "
                f"valid section prefixes: {sorted(prefixes)}")
        return cls(**{name: sec.from_env(env)
                      for name, sec in sections.items()})

    def to_platform_config(self):
        """The fully-wired ``PlatformConfig``: transport knobs from the
        platform section, depth-logger intervals from observability."""
        pc = self.platform.to_platform_config()
        pc.queue_depth_interval = self.observability.queue_depth_interval
        pc.process_depth_interval = self.observability.process_depth_interval
        pc.tenancy = self.tenancy.enabled
        pc.tenancy_tenants = self.tenancy.tenants
        pc.tenancy_default_weight = self.tenancy.default_weight
        pc.tenancy_default_rps = self.tenancy.default_rps
        pc.tenancy_default_burst = self.tenancy.default_burst
        pc.tenancy_label_top_n = self.tenancy.label_top_n
        pc.tenancy_goodput_target = self.tenancy.goodput_target
        pc.tenancy_min_quantum = self.tenancy.min_quantum
        pc.rollout_drain_eject_ttl_s = self.rollout.drain_eject_ttl_s
        return pc

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)
