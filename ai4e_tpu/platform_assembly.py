"""Single-process platform assembly — store + broker + dispatchers + gateway.

The reference wires its components together with 15 bash deployment scripts
(``InfrastructureDeployment/deploy_infrastructure.sh:5-38``); this module is
the same wiring as code, used by tests, local development, and single-host
deployments. Multi-host deployments run the pieces separately (taskstore HTTP
service + broker + gateway) — see ``deploy/``.
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass

from .broker import DispatcherPool, InMemoryBroker
from .gateway import Gateway
from .metrics import DEFAULT_REGISTRY, MetricsRegistry
from .service import APIService, LocalTaskManager
from .utils.backends import Weighted, normalize_backends
from .taskstore import InMemoryTaskStore, TaskStatus, endpoint_path


@dataclass
class PlatformConfig:
    transport: str = "queue"        # "queue" | "push" (setup_env.sh:11 TRANSPORT_TYPE)
    retry_delay: float = 60.0       # dispatcher backoff on 429/503 (setup_env.sh:74)
    max_delivery_count: int = 1440  # broker patience (setup_env.sh:65)
    dispatcher_concurrency: int = 1  # serial per queue (host.json:5-9)
    journal_path: str | None = None  # None → pure in-memory store
    # Journal fsync policy (docs/durability.md): "never" (default —
    # write+flush, today's behavior: survives SIGKILL, loses the unsynced
    # tail on a machine crash), "always" (fsync per append), or
    # "group:<ms>" (batched group commit, crash window bounded by the
    # window). None resolves the AI4E_TASKSTORE_FSYNC env knob.
    taskstore_fsync: str | None = None
    lease_seconds: float = 300.0
    native_broker: bool = False      # C++ broker core (native/broker_core.cpp)
    native_store: bool = False       # C++ task-store core (native/taskstore_core.cpp)
    queue_depth_interval: float = 30.0    # TaskQueueLogger.cs:19
    process_depth_interval: float = 300.0  # TaskProcessLogger.cs:21
    # push-transport delivery policy (deploy_event_grid_subscription.sh:37)
    push_ttl_seconds: float = 300.0
    push_max_attempts: int = 3
    push_window: int = 256          # concurrent in-flight deliveries
    # stuck-task watchdog (taskstore/reaper.py); None disables
    reaper_running_timeout: float | None = None
    reaper_interval: float = 30.0
    reaper_max_requeues: int = 3
    # Terminal-history retention (seconds): completed/failed tasks older
    # than this are evicted (memory + journal bound); None keeps forever.
    # None = AUTO (15 min on the Python store, off on the native store);
    # >=0 = explicit retention seconds (0 = evict terminal tasks
    # immediately, the pre-r5 meaning, preserved); < 0 = explicitly keep
    # history forever.
    reaper_terminal_retention: float | None = None
    # Object-store slot for large results (assign_storage_auth_to_aks.sh:9-17):
    # results >= the threshold are written under result_dir (a local dir, PD,
    # or GCS FUSE mount) instead of store memory. None dir disables offload.
    result_dir: str | None = None
    result_offload_threshold: int = 1024 * 1024
    # Control-plane HA (taskstore/replication.py): when set, this platform
    # boots as a STANDBY — its store is a FollowerTaskStore tailing the
    # primary's journal stream at this URL; a watchdog promotes it (and
    # starts transport + re-seeds dispatch) when the primary dies. Requires
    # journal_path. The availability slot managed Redis filled for the
    # reference (deploy_cache_prerequisites.sh:15-31).
    replicate_from: str | None = None
    failover_interval: float = 2.0
    failover_down_after: int = 3
    # Subscription key for the journal stream when the primary's control
    # plane runs keyed (the task-store surface rides the gateway app behind
    # the key middleware — an unkeyed replicator would 401 forever and the
    # standby would never sync).
    replicate_api_key: str | None = None
    # This node's control-plane URL as PEERS reach it. After a promotion
    # the fencing prober includes it in demote calls so the deposed
    # primary's platform rejoins the new primary as a follower
    # automatically; unset, deposed peers are fenced (writes refused) but
    # must be re-seeded by the deployment.
    advertise_url: str | None = None
    # Inference result cache + single-flight coalescing (rescache/): the
    # gateway answers repeat requests without dispatching, concurrent
    # identical requests share ONE execution, and dispatchers complete
    # redeliveries from the cache. Off by default — enabling it is a
    # semantic statement that identical payloads may share results
    # (docs/rescache.md; per-request opt-out via X-Cache-Bypass).
    result_cache: bool = False
    cache_max_entries: int = 4096
    cache_max_bytes: int = 256 * 1024 * 1024
    # Entry lifetime bound. In a single-process deployment the reload hook
    # invalidates synchronously; TTL is the staleness backstop for caches
    # that a remote worker's reload cannot reach. None = no TTL.
    cache_ttl_seconds: float | None = 300.0
    # Admission control (admission/, docs/admission.md): end-to-end
    # deadline propagation (X-Deadline-Ms / X-Priority / X-Shed-Reason),
    # priority load shedding with drain-rate-derived Retry-After, and an
    # adaptive (gradient/AIMD) concurrency limit replacing the fixed
    # gateway sync cap and dispatcher fan-out. Off by default — enabling
    # it is a semantic statement that the platform may refuse or expire
    # work (terminal `expired` status) instead of carrying every request
    # to completion however late.
    admission: bool = False
    admission_min_limit: int = 1
    admission_max_limit: int = 256
    admission_initial_limit: int = 8
    # Async-edge backlog capacity the priority shedder fractions divide
    # (created-set depth per route; background sheds first at 60%).
    admission_max_backlog: int = 1024
    # Resilient routing under failure (resilience/, docs/resilience.md):
    # a per-backend circuit breaker shared by the gateway sync proxy and
    # every dispatcher (open backends ejected from weighted picks, their
    # weight redistributed; half-open probes re-admit them), plus
    # budget-bounded in-delivery retries with failover to a different
    # backend on connection error and 5xx-as-transient redelivery. Off by
    # default — enabling it is a semantic statement that 5xx responses
    # are transient (retried/redelivered, not instantly terminal) and
    # that redeliveries of already-terminal tasks are suppressed.
    resilience: bool = False
    resilience_failure_threshold: int = 5   # consecutive failures to trip
    resilience_window: int = 16             # rolling error-rate window
    resilience_error_rate: float = 0.5      # window fraction that trips
    resilience_recovery_seconds: float = 30.0  # open → half-open cooldown
    resilience_max_attempts: int = 3        # POST attempts per delivery
    resilience_retry_base_s: float = 0.05   # first in-delivery retry delay
    resilience_retry_budget_ratio: float = 0.2  # retries per request, steady
    # Deadline-aware orchestration over unequal backends (orchestration/,
    # docs/orchestration.md): per-request placement on predicted
    # completion-within-deadline and per-backend cost (replacing the
    # health-weighted random pick in the dispatchers and sync proxy), the
    # brownout degradation ladder consulted by the admission shedder, and
    # predictive autoscaling (arrival/drain projection instead of raw
    # depth; per-shard decisions through one actuator on sharded routes).
    # Off by default — enabling it is a semantic statement that backends
    # are UNEQUAL (placement prefers cheap tiers that clear the deadline
    # bar) and that sustained predicted-miss pressure may brown the
    # platform out class by class. Requires admission AND resilience —
    # it composes their signals rather than inventing new ones.
    orchestration: bool = False
    orchestration_confidence: float = 0.75   # p_within bar a backend clears
    orchestration_window: int = 256          # RTT samples per backend sketch
    orchestration_horizon_s: float = 60.0    # sample decay horizon (s)
    # "substring=cost,..." relative backend cost (first match wins,
    # unmatched = 1.0) — e.g. "tpu=3,cpu-fallback=1,remote=5".
    orchestration_costs: str | None = None
    orchestration_ladder_up: float = 0.3     # pressure that steps the ladder up
    orchestration_ladder_down: float = 0.1   # pressure that steps it down
    orchestration_ladder_hold_s: float = 5.0  # sustain per step (hysteresis)
    orchestration_scale_horizon_s: float = 10.0  # predictive-scale projection
    # Sharded task store (taskstore/sharding.py, docs/sharding.md): split
    # the task keyspace over N independent shards — each with its own
    # journal, passive replicas (with journal_path), and epoch-fenced
    # failover — so one shard primary's death degrades 1/N of the keyspace
    # for the promotion window instead of everything. 1 (default) keeps
    # today's single-store assembly byte for byte. >1 requires the Python
    # store/broker and is exclusive with the whole-store HA pair
    # (replicate_from) — shard replicas ARE the availability story.
    task_shards: int = 1
    # Hash-slot count the ring divides the keyspace into (a rebalance moves
    # whole slots); must be >= task_shards.
    task_shard_slots: int = 64
    # Passive replicas per shard (journal_path required for them to absorb);
    # 0 disables per-shard failover.
    task_shard_replicas: int = 1
    # Replica journal-tail poll interval (seconds).
    shard_tail_interval: float = 0.25
    # Per-shard change-feed replay window (terminal records retained for
    # the long-poll attach race; taskstore/feed.py).
    shard_feed_recent: int = 4096
    # Request observability (observability/, docs/observability.md):
    # per-task hop ledger stamped at every hop and carried on the task
    # record (``GET /v1/taskmanagement/task/{id}?ledger=1``, the trace
    # CLI), a tail-sampled flight recorder keeping 100% of slow/failed/
    # expired/shed/failovered request timelines (``GET /v1/debug/flight``,
    # dumped by the chaos harness on invariant violation), and the
    # per-route e2e latency/outcome telemetry the SLO engine reads. Off
    # by default — the assembly is byte-identical without it (asserted
    # in tests); requires the Python store (the native core has no
    # ledger slot).
    observability: bool = False
    flight_capacity: int = 512
    flight_sample: float = 0.05       # kept fraction of boring requests
    flight_slow_ms: float = 1000.0    # e2e latency that makes one interesting
    # Per-route SLO objectives ("/route=<latency_ms>:<target_pct>" or
    # "/route=goodput:<target_pct>", comma-separated) + the multi-window
    # burn-rate engine exporting ai4e_slo_* (observability/slo.py).
    # Requires observability=True (the engine reads its histograms).
    slo_objectives: str | None = None
    slo_tick_s: float = 5.0
    slo_fast_window_s: float = 300.0
    slo_slow_window_s: float = 3600.0
    # Sustained SLO breaches feed the degradation ladder as an extra
    # miss-evidence source (requires orchestration).
    slo_ladder: bool = False
    # First-class pipeline DAGs (pipeline/, docs/pipelines.md): declared
    # multi-stage compositions (fan-out/fan-in joins with a failure
    # quorum, per-stage deadline fractions carved from X-Deadline-Ms,
    # per-stage result-cache reuse) executed under ONE TaskId by a
    # coordinator riding the existing store/broker/dispatcher fabric,
    # plus the streaming surface GET /v1/taskmanagement/task/{id}/events
    # (SSE: stage-by-stage partial results before the terminal answer).
    # Off by default — the assembly is byte-identical without it
    # (asserted in tests); requires the Python store/broker and the
    # queue transport (the coordinator consumes entry queues).
    pipeline: bool = False
    # Per-task event replay buffer (events a late-attaching stream still
    # sees) and the SSE stream's maximum duration per request (seconds;
    # ?wait= may only shorten it).
    pipeline_event_replay: int = 256
    pipeline_stream_max_s: float = 300.0
    # Trailing CHUNK events (token streams) a late attacher replays
    # before the bounded history drops to a single `truncated` marker
    # (docs/streaming.md).
    pipeline_chunk_replay: int = 128
    # Multi-tenancy (tenancy/, docs/tenancy.md): subscription keys resolve
    # to tenants once at the gateway edge; work-creating requests spend a
    # per-tenant token bucket (429 + drain-derived Retry-After, composed
    # with the priority shedder); the broker's per-shard sub-queues dequeue
    # deficit-round-robin across per-tenant lanes so a flooded tenant fills
    # its own lane, never another's; the dispatcher charges placement cost
    # per tenant; and goodput/SLO-burn series carry a bounded-cardinality
    # tenant label (top-N + "other", never raw keys). Off by default — the
    # assembly is byte-identical without it (asserted in tests); requires
    # the Python store/broker and the queue transport (the native broker's
    # C structs carry no tenant slot, and the push transport has no queue
    # to lane).
    tenancy: bool = False
    # Tenant spec "name=key1|key2[:weight[:rps[:burst]]]" comma-separated
    # (tenancy/registry.py parse_tenants); None/"" = no declared tenants
    # (all traffic rides the default tenant's lane and bucket).
    tenancy_tenants: str | None = None
    # Defaults for spec entries that omit a field — and the default
    # tenant's own policy (rps 0 = unlimited).
    tenancy_default_weight: float = 1.0
    tenancy_default_rps: float = 0.0
    tenancy_default_burst: float = 0.0
    # Frozen metric-label cardinality bound: first N declared tenants keep
    # their id as label value, the rest collapse into "other".
    tenancy_label_top_n: int = 8
    # Goodput target the per-tenant SLO-burn gauge normalizes against
    # (burn 1.0 = failing exactly (1 - target) of the window).
    tenancy_goodput_target: float = 0.99
    # Floor on a lane's DRR credit per ring visit (guards pathological
    # weights; tenancy/lanes.py).
    tenancy_min_quantum: float = 0.05
    # How long a drain-marked backend (503 + X-Draining) stays ejected
    # from placement per observation (rollout/; AI4E_ROLLOUT_
    # DRAIN_EJECT_TTL_S feeds this through FrameworkConfig).
    rollout_drain_eject_ttl_s: float = 30.0


class LocalPlatform:
    """Everything the async path needs, in one event loop.

    Usage::

        platform = LocalPlatform(PlatformConfig(retry_delay=0.05))
        svc = platform.make_service("megadetector", prefix="v1/camera-trap")
        ... register endpoints on svc ...
        platform.publish_async_api("/v1/camera-trap/detect",
                                   backend_uri="http://127.0.0.1:8083/v1/camera-trap/detect")
        await platform.start()
    """

    def __init__(self, config: PlatformConfig | None = None,
                 metrics: MetricsRegistry | None = None):
        self.config = config or PlatformConfig()
        self.metrics = metrics or DEFAULT_REGISTRY
        result_backend = None
        if self.config.result_dir:
            from .taskstore.results import FileResultBackend
            result_backend = FileResultBackend(self.config.result_dir)
        result_kwargs = dict(
            result_backend=result_backend,
            result_offload_threshold=(self.config.result_offload_threshold
                                      if result_backend else None))
        # Journal-bearing stores additionally get the fsync policy and the
        # assembly registry (ai4e_journal_* metrics must land beside the
        # platform's own /metrics, not in the process default — AIL002).
        journal_kwargs = dict(result_kwargs,
                              fsync=self.config.taskstore_fsync,
                              metrics=self.metrics)
        if self.config.task_shards > 1:
            if self.config.native_store or self.config.native_broker:
                raise ValueError(
                    "task_shards > 1 requires the Python store and broker "
                    "(the native cores hold no ring/fence state)")
            if self.config.replicate_from:
                raise ValueError(
                    "task_shards > 1 is exclusive with replicate_from: "
                    "per-shard replicas are the sharded availability "
                    "story (docs/sharding.md)")
            from .taskstore.sharding import ShardedTaskStore
            self.store = ShardedTaskStore(
                self.config.task_shards,
                slots=self.config.task_shard_slots,
                journal_path=self.config.journal_path,
                replicas=(self.config.task_shard_replicas
                          if self.config.journal_path else 0),
                tail_interval=self.config.shard_tail_interval,
                feed_recent=self.config.shard_feed_recent,
                **journal_kwargs)
        elif self.config.replicate_from:
            if not self.config.journal_path:
                raise ValueError(
                    "replicate_from (standby mode) requires journal_path — "
                    "the follower journals the absorbed stream")
            if self.config.native_store:
                raise ValueError("standby mode requires the Python store")
            from .taskstore.store import FollowerTaskStore
            self.store = FollowerTaskStore(self.config.journal_path,
                                           **journal_kwargs)
        elif self.config.journal_path:
            if self.config.native_store:
                raise ValueError(
                    "native_store has no journal; use journal_path with the "
                    "Python store or native_store without durability")
            # Born-primary FollowerTaskStore, not a plain JournaledTaskStore:
            # behaviorally identical while primary, but carries the
            # demote()/note_epoch() fence — so a journaled primary in an HA
            # pair can be deposed by a promoted standby (split-brain
            # fencing, VERDICT r4 #3) instead of silently accepting
            # doomed writes.
            from .taskstore.store import FollowerTaskStore
            self.store = FollowerTaskStore(self.config.journal_path,
                                           start_as_primary=True,
                                           **journal_kwargs)
        elif self.config.native_store:
            from .taskstore.native import NativeTaskStore
            if result_backend is not None:
                raise ValueError(
                    "result_dir offload requires the Python store "
                    "(the native store keeps results in its own memory)")
            ret = self.config.reaper_terminal_retention
            if ret is not None and ret >= 0:
                # Fail loudly on an EXPLICIT retention: a knob that
                # silently never evicts is exactly the OOM it exists to
                # prevent. (AUTO/None and negative opt-out both mean no
                # eviction here — the native store has none.)
                raise ValueError(
                    "reaper_terminal_retention requires the Python store "
                    "(the native store has no eviction)")
            self.store = NativeTaskStore()
        else:
            self.store = InMemoryTaskStore(**result_kwargs)
        self.task_manager = LocalTaskManager(self.store)
        self.result_cache = None
        if self.config.result_cache:
            from .rescache import ResultCache, attach_store
            self.result_cache = ResultCache(
                max_entries=self.config.cache_max_entries,
                max_bytes=self.config.cache_max_bytes,
                ttl_s=self.config.cache_ttl_seconds,
                metrics=self.metrics)
            if hasattr(self.store, "add_listener"):
                # The async path's fill point: the store's change feed
                # copies results into the cache on terminal transitions and
                # releases single-flight leaders (rescache/wiring.py).
                # Every store qualifies — the native facade shares the
                # StoreSideEffects listener plumbing and carries CacheKey
                # in a Python-side sidecar (native.py) — the hasattr is
                # only a guard for exotic store substitutes in tests.
                attach_store(self.store, self.result_cache)
        self.admission = None
        if self.config.admission:
            if self.config.native_store or self.config.native_broker:
                # The C cores have no deadline/priority slots on their
                # record/message structs and no `expired` status bucket in
                # their canonical sets — admission there would silently
                # drop the very state it exists to enforce. Same loud-fail
                # pattern as retention/journal on the native store.
                raise ValueError(
                    "admission control requires the Python store and "
                    "broker (the native cores carry no deadline/priority "
                    "state)")
            from .admission import AdmissionController
            self.admission = AdmissionController(
                metrics=self.metrics,
                min_limit=self.config.admission_min_limit,
                max_limit=self.config.admission_max_limit,
                initial_limit=self.config.admission_initial_limit,
                max_backlog=self.config.admission_max_backlog)
            if hasattr(self.store, "add_listener"):
                # Terminal transitions feed the drain-rate estimator (the
                # Retry-After on every shed/standby response) and score
                # goodput — the same change feed the long-poll waiters and
                # the result cache ride.
                self.admission.attach_store(self.store)
        self.resilience = None
        if self.config.resilience:
            # ONE health model per assembly: the sync proxy and every
            # dispatcher record into (and route around) the same breakers,
            # so a backend melting under queue deliveries is ejected from
            # sync picks too.
            from .resilience import BackendHealth, ResiliencePolicy
            self.resilience = BackendHealth(
                policy=ResiliencePolicy(
                    failure_threshold=self.config.resilience_failure_threshold,
                    window=self.config.resilience_window,
                    error_rate=self.config.resilience_error_rate,
                    recovery_seconds=self.config.resilience_recovery_seconds,
                    max_attempts=self.config.resilience_max_attempts,
                    retry_base_s=self.config.resilience_retry_base_s,
                    retry_budget_ratio=(
                        self.config.resilience_retry_budget_ratio),
                    drain_eject_ttl_s=(
                        self.config.rollout_drain_eject_ttl_s)),
                metrics=self.metrics)
        self.orchestration = None
        if self.config.orchestration:
            if self.admission is None or self.resilience is None:
                # The orchestrator composes the admission layer's
                # deadline/drain signals and the resilience layer's
                # breaker state — without either it would be guessing.
                # Loud fail, same pattern as admission-on-native.
                raise ValueError(
                    "orchestration=True requires admission=True and "
                    "resilience=True (it composes their signals — "
                    "docs/orchestration.md)")
            from .orchestration import (Orchestrator, OrchestrationPolicy,
                                        parse_costs)
            self.orchestration = Orchestrator(
                self.resilience,
                policy=OrchestrationPolicy(
                    confidence=self.config.orchestration_confidence,
                    window=self.config.orchestration_window,
                    horizon_s=self.config.orchestration_horizon_s,
                    costs=parse_costs(self.config.orchestration_costs),
                    ladder_up=self.config.orchestration_ladder_up,
                    ladder_down=self.config.orchestration_ladder_down,
                    ladder_hold_s=self.config.orchestration_ladder_hold_s,
                    scale_horizon_s=(
                        self.config.orchestration_scale_horizon_s)),
                metrics=self.metrics)
            # The admission shedder consults the ladder on every decision,
            # and its store listener feeds the ladder actual deadline
            # outcomes (late/expired) — the brownout's evidence loop.
            self.admission.set_ladder(self.orchestration.ladder)
        self.observability = None
        self.slo = None
        if self.config.observability:
            if self.config.native_store:
                # The C store has no ledger slot; silently running the
                # layer without timelines would be the worst outcome —
                # same loud-fail pattern as admission-on-native.
                raise ValueError(
                    "observability=True requires the Python store "
                    "(the native core carries no hop-ledger state)")
            from .observability.flight import FlightRecorder
            from .observability.hub import RequestObservability
            self.observability = RequestObservability(
                self.store, metrics=self.metrics,
                flight=FlightRecorder(
                    capacity=self.config.flight_capacity,
                    sample=self.config.flight_sample,
                    slow_ms=self.config.flight_slow_ms,
                    metrics=self.metrics))
        if self.config.slo_objectives:
            if self.observability is None:
                raise ValueError(
                    "slo_objectives requires observability=True — the "
                    "SLO engine reads the e2e histograms the "
                    "observability layer maintains "
                    "(docs/observability.md)")
            from .observability.slo import SloEngine, parse_objectives
            self.slo = SloEngine(
                parse_objectives(self.config.slo_objectives),
                metrics=self.metrics,
                fast_window_s=self.config.slo_fast_window_s,
                slow_window_s=self.config.slo_slow_window_s,
                tick_s=self.config.slo_tick_s)
        if self.config.slo_ladder:
            if self.slo is None or self.orchestration is None:
                raise ValueError(
                    "slo_ladder=True requires slo_objectives AND "
                    "orchestration=True — it feeds SLO breaches to the "
                    "degradation ladder (docs/observability.md)")
            self.slo.attach_ladder(self.orchestration.ladder)
        self.tenancy = None
        if self.config.tenancy:
            if self.config.transport != "queue":
                raise ValueError(
                    "tenancy=True requires the queue transport — the "
                    "weighted-fair lanes live inside the broker's queues "
                    "(docs/tenancy.md)")
            if self.config.native_store or self.config.native_broker:
                # The C structs have no tenant slot; running the layer
                # there would silently drop the very scope it enforces —
                # same loud-fail pattern as admission-on-native.
                raise ValueError(
                    "tenancy=True requires the Python store and broker "
                    "(the native cores carry no tenant state)")
            from .tenancy import Tenancy
            self.tenancy = Tenancy.from_spec(
                self.config.tenancy_tenants,
                metrics=self.metrics,
                default_weight=self.config.tenancy_default_weight,
                default_rps=self.config.tenancy_default_rps,
                default_burst=self.config.tenancy_default_burst,
                label_top_n=self.config.tenancy_label_top_n,
                goodput_target=self.config.tenancy_goodput_target,
                min_quantum=self.config.tenancy_min_quantum)
            if hasattr(self.store, "add_listener"):
                # Terminal transitions label the per-tenant outcome/burn
                # series — the same change feed admission's goodput scorer
                # rides, attached independently so per-tenant series exist
                # without the observability layer.
                self.tenancy.attach_store(self.store)
        self.broker = None
        self.dispatchers = None
        self.topic = None
        self.webhook = None
        self._webhook_runner = None
        if self.config.transport == "push":
            # Webhook routes are recorded so a demoted-then-re-promoted
            # node can rebuild the push transport (demote_now closes it).
            self._push_routes: list[tuple[str, Weighted]] = []
            self._build_push()
        elif self.config.transport == "queue":
            if self.config.native_broker:
                from .broker.native import NativeBroker
                self.broker = NativeBroker(
                    max_delivery_count=self.config.max_delivery_count,
                    lease_seconds=self.config.lease_seconds)
            else:
                self.broker = InMemoryBroker(
                    max_delivery_count=self.config.max_delivery_count,
                    lease_seconds=self.config.lease_seconds,
                    metrics=self.metrics,
                    # Sharded store → per-shard sub-queues, so each shard's
                    # dispatchers drain independently (broker/queue.py).
                    shard_router=(self.store.shard_for
                                  if self.config.task_shards > 1 else None),
                    # Tenancy → per-tenant DRR lanes inside every queue,
                    # shard sub-queues included (broker/queue.py).
                    fair=(self.tenancy.lanes
                          if self.tenancy is not None else None))
            self.store.set_publisher(self.broker.publish)
            self.dispatchers = DispatcherPool(
                self.broker, self.task_manager,
                retry_delay=self.config.retry_delay,
                concurrency=self.config.dispatcher_concurrency,
                result_cache=self.result_cache,
                result_store=(self.store if self.result_cache is not None
                              and hasattr(self.store, "set_result")
                              else None),
                admission=self.admission,
                resilience=self.resilience,
                orchestration=self.orchestration,
                observability=self.observability,
                tenancy=self.tenancy,
                metrics=self.metrics)
        else:
            raise ValueError(
                f"unknown transport {self.config.transport!r}; "
                "expected 'queue' or 'push'")
        self.pipeline = None
        self.task_events = None
        if self.config.pipeline:
            if self.config.transport != "queue":
                raise ValueError(
                    "pipeline=True requires the queue transport — the "
                    "coordinator consumes pipeline entry queues "
                    "(docs/pipelines.md)")
            if self.config.native_store or self.config.native_broker:
                raise ValueError(
                    "pipeline=True requires the Python store and broker "
                    "(the coordinator rides the store change feed and "
                    "stage sub-records)")
            from .pipeline import PipelineCoordinator, TaskEventHub
            self.task_events = TaskEventHub(
                replay=self.config.pipeline_event_replay,
                chunk_replay=self.config.pipeline_chunk_replay,
                metrics=self.metrics)
            # Every transition of a tracked/streamed task becomes a
            # `status` event; terminal transitions close streams — the
            # same change feed the long-poll waiters and the result
            # cache ride.
            self.task_events.attach_store(self.store)
            queue_names = None
            if self.config.task_shards > 1:
                from .broker.queue import shard_queue_name
                n = self.config.task_shards

                def queue_names(path, _n=n):
                    return [shard_queue_name(path, i) for i in range(_n)]

            self.pipeline = PipelineCoordinator(
                self.store, self.broker, hub=self.task_events,
                result_cache=self.result_cache, admission=self.admission,
                observability=self.observability, metrics=self.metrics,
                queue_names=queue_names)
        self.gateway = Gateway(self.store, metrics=self.metrics)
        if self.result_cache is not None:
            self.gateway.set_result_cache(self.result_cache)
        if self.admission is not None:
            self.gateway.set_admission(self.admission)
        if self.resilience is not None:
            self.gateway.set_resilience(self.resilience)
        if self.orchestration is not None:
            self.gateway.set_orchestration(self.orchestration)
        if self.observability is not None:
            self.gateway.set_observability(self.observability)
        if self.tenancy is not None:
            self.gateway.set_tenancy(self.tenancy)
        if self.task_events is not None:
            self.gateway.set_event_stream(
                self.task_events,
                max_stream_s=self.config.pipeline_stream_max_s)
        # Terminal-history retention: None = AUTO — 15 min on the Python
        # store, sized to the soak evidence (unevicted terminal history
        # grows ~12 MB/min at 200 req/s → AUTO bounds steady-state at
        # ~180 MB, the level the retention-on soak measured flat;
        # bench_results/r5-cpu/). 0 keeps its pre-r5 meaning (evict
        # terminal tasks immediately); NEGATIVE opts out of eviction
        # entirely. Nothing on the native store (no eviction support).
        # Redis expiry played this role for the reference.
        retention = self.config.reaper_terminal_retention
        if retention is None and not self.config.native_store:
            retention = 900.0
        if retention is not None and retention < 0:
            retention = None
        self.reaper = None
        if (self.config.reaper_running_timeout is not None
                or retention is not None):
            from .taskstore.reaper import TaskReaper
            self.reaper = TaskReaper(
                self.store,
                running_timeout=self.config.reaper_running_timeout,
                interval=self.config.reaper_interval,
                max_requeues=self.config.reaper_max_requeues,
                terminal_retention=retention,
                metrics=self.metrics)
        from .observability import DepthLogger
        self.depth_logger = DepthLogger(
            self.store, metrics=self.metrics,
            queue_interval=self.config.queue_depth_interval,
            process_interval=self.config.process_depth_interval)
        self.services: list[APIService] = []
        self.autoscalers: list = []
        self.replicator = None
        self.watchdog = None
        self.prober = None
        self._transport_running = False
        self._started = False
        # Strong refs to fire-and-forget background work (dead-letter
        # terminal transitions): the event loop holds tasks WEAKLY, so a
        # dropped create_task handle can be garbage-collected mid-flight
        # and the task it was failing sits non-terminal forever (AIL004).
        self._bg_tasks: set[asyncio.Task] = set()

    # -- assembly ----------------------------------------------------------

    def _build_push(self) -> None:
        """(Re)construct the push transport: topic + webhook dispatcher +
        recorded routes, and point the store's publish hook at the new
        topic. Called at assembly and again after a demotion closed the
        previous topic (PushTopic.aclose is terminal — a re-promotion
        needs a fresh one)."""
        from .broker.push import PushTopic, WebhookDispatcher
        self.topic = PushTopic(
            ttl_seconds=self.config.push_ttl_seconds,
            max_attempts=self.config.push_max_attempts,
            retry_delay=self.config.retry_delay,
            window=self.config.push_window,
            metrics=self.metrics)
        self.webhook = WebhookDispatcher(self.task_manager,
                                         metrics=self.metrics)
        for queue_name, backend_uri in self._push_routes:
            self.webhook.add_route(queue_name, backend_uri)
        self.store.set_publisher(self.topic.publish)

    def make_service(self, name: str, prefix: str = "") -> APIService:
        svc = APIService(name, prefix=prefix,
                         task_manager=self.task_manager, metrics=self.metrics)
        self.services.append(svc)
        return svc

    def publish_async_api(self, public_prefix: str, backend_uri,
                          retry_delay: float | None = None,
                          concurrency: int | None = None,
                          autoscale=None,
                          autoscale_interval: float = 5.0,
                          max_body_bytes: int | None = None) -> None:
        """Register an async API end-to-end: gateway route + dispatcher for
        its queue (the reference needs an APIM operation + a Service Bus queue
        + a function app per API; here it's one call). Passing an
        ``AutoscalePolicy`` as ``autoscale`` attaches the HPA-style control
        loop (the reference's per-API ``autoscaler.yaml``) to the
        dispatcher's delivery fan-out. ``backend_uri`` may be a weighted
        backend LIST (canary; ``utils/backends.py``) — the recorded task
        Endpoint is the primary's (path identity is shared by
        construction), deliveries split per the weights."""
        backends = normalize_backends(backend_uri)
        # The gateway derives cacheability from the backend set itself
        # (weighted canary splits are uncacheable — Route.cacheable).
        self.gateway.add_async_route(public_prefix, backends,
                                     max_body_bytes=max_body_bytes)
        self.register_internal_route(backends, retry_delay=retry_delay,
                                     concurrency=concurrency,
                                     autoscale=autoscale,
                                     autoscale_interval=autoscale_interval)

    def register_internal_route(self, backend_uri,
                                retry_delay: float | None = None,
                                concurrency: int | None = None,
                                autoscale=None,
                                autoscale_interval: float = 5.0) -> None:
        """Transport consumer for a backend WITHOUT a public gateway route —
        internal pipeline stages (e.g. the classifier batch endpoint a
        detector's crops handoff targets) are reachable only by republished
        tasks, never by clients. Accepts a weighted backend list (canary)."""
        backend_uri = normalize_backends(backend_uri)
        queue_name = endpoint_path(backend_uri[0][0])
        if self.config.transport == "push":
            if autoscale is not None or retry_delay is not None or concurrency is not None:
                raise ValueError(
                    "autoscale/retry_delay/concurrency are queue-transport "
                    "knobs; push retry policy is topic-wide "
                    "(PlatformConfig.retry_delay/push_max_attempts)")
            self._push_routes.append((queue_name, backend_uri))
            self.webhook.add_route(queue_name, backend_uri)
            return
        self.broker.register_queue(queue_name)
        if self.config.task_shards > 1:
            if autoscale is not None and self.orchestration is None:
                # PR 6's two-loops/one-actuator refusal, now relaxed ONLY
                # under orchestration: the predictive sharded controller
                # makes per-shard decisions but routes them through one
                # actuator (ShardScaleTarget), so there is still exactly
                # one writer per dispatcher's concurrency.
                raise ValueError(
                    "autoscale policies are per-dispatcher; with "
                    "task_shards > 1 use admission's adaptive control "
                    "(one limiter per shard sub-queue) instead — or "
                    "enable orchestration, whose predictive scaler "
                    "routes per-shard decisions through one actuator "
                    "(docs/orchestration.md)")
            from .broker.queue import shard_queue_name
            queue_names = [shard_queue_name(queue_name, i)
                           for i in range(self.config.task_shards)]
        else:
            queue_names = [queue_name]
        dispatchers = [self.dispatchers.register(qn, backend_uri,
                                                 retry_delay=retry_delay,
                                                 concurrency=concurrency)
                       for qn in queue_names]
        if autoscale is not None:
            self._attach_autoscaler(queue_names, dispatchers, autoscale,
                                    autoscale_interval)
        elif self.admission is not None:
            # The adaptive controller owns each dispatcher's fan-out: its
            # per-queue limiter (fed by delivery RTTs + backpressure
            # backoffs) replaces the fixed concurrency constant. An
            # explicit AutoscalePolicy wins — two control loops driving
            # one actuator would fight.
            for qn, dispatcher in zip(queue_names, dispatchers):
                self.admission.add_target("dispatch:" + qn,
                                          dispatcher.set_concurrency)

    def _attach_autoscaler(self, queue_names, dispatchers, policy,
                           interval) -> None:
        """HPA-style scaling for a route's dispatcher(s). Under
        orchestration the signal is PREDICTIVE — projected backlog from
        the admission controller's arrival/drain estimators
        (``scaling.predictive_signal``) instead of raw depth, so loops
        scale ahead of the deadline-miss cliff; sharded routes get one
        ``ShardedAutoscaleController`` (per-shard decisions, one
        actuator)."""
        from .scaling import (AutoscaleController, DispatcherScaleTarget,
                              ShardScaleTarget, ShardedAutoscaleController,
                              predictive_signal)
        base_path = dispatchers[0].route_path
        if len(dispatchers) > 1:
            # Sharded (only reachable under orchestration — the refusal
            # above): per-shard depth from each shard's own store, the
            # global arrival/drain imbalance split evenly across shards
            # (the ring spreads TaskIds uniformly).
            horizon = self.orchestration.policy.scale_horizon_s
            n = len(dispatchers)

            def shard_depth(i, p=base_path):
                def depth() -> float:
                    # Resolved per tick, not captured: a shard failover
                    # swaps the promoted replica in for the dead primary,
                    # and a captured store object would read the corpse's
                    # frozen counts forever.
                    s = self.store.shard_stores()[i]
                    return (s.set_len(p, "created")
                            + s.set_len(p, "running"))
                return depth

            shards = []
            for i, qn in enumerate(queue_names):
                # THIS route's rates (not the platform-global ones — a
                # flooded sibling route must not inflate this route's
                # projection), split evenly across its shards (the ring
                # spreads TaskIds uniformly).
                shards.append((qn, predictive_signal(
                    shard_depth(i),
                    lambda p=base_path, n=n: (
                        self.admission.arrival_rate(route=p) / n),
                    lambda p=base_path, n=n: (
                        self.admission.route_drain_rate(p) / n),
                    horizon)))
            self.autoscalers.append(ShardedAutoscaleController(
                shards, ShardScaleTarget(dispatchers), policy=policy,
                interval=interval, metrics=self.metrics))
            return
        signal = None
        if self.orchestration is not None:
            store = self.store
            signal = predictive_signal(
                lambda: (store.set_len(base_path, "created")
                         + store.set_len(base_path, "running")),
                lambda p=base_path: self.admission.arrival_rate(route=p),
                lambda p=base_path: self.admission.route_drain_rate(p),
                self.orchestration.policy.scale_horizon_s)
        self.autoscalers.append(AutoscaleController(
            self.store, queue_names[0],
            DispatcherScaleTarget(dispatchers[0]),
            policy=policy, interval=interval, signal=signal,
            metrics=self.metrics))

    def register_pipeline(self, spec, max_body_bytes: int | None = None
                          ) -> None:
        """Publish a declared pipeline DAG (``pipeline.PipelineSpec``,
        ``docs/pipelines.md``): one gateway async route at ``spec.prefix``
        whose tasks are consumed by the pipeline coordinator instead of a
        backend dispatcher — stages then run as sub-tasks through the
        ordinary fabric. Stage ENDPOINTS still need transport consumers:
        register each one with ``register_internal_route`` (internal
        stages) or ``publish_async_api`` (stages that are also public
        APIs), exactly like hop-to-hop pipeline stages today."""
        if self.pipeline is None:
            raise ValueError(
                "register_pipeline requires PlatformConfig(pipeline=True)")
        self.gateway.add_async_route(spec.prefix, spec.entry_path,
                                     max_body_bytes=max_body_bytes)
        self.pipeline.register(spec)

    def publish_sync_api(self, public_prefix: str, backend_uri,
                         max_body_bytes: int | None = None) -> None:
        self.gateway.add_sync_route(public_prefix, backend_uri,
                                    max_body_bytes=max_body_bytes)

    # -- lifecycle ---------------------------------------------------------

    async def start(self) -> None:
        loop = asyncio.get_running_loop()
        if self.config.replicate_from:
            # Standby: tail the primary's journal, serve reads, refuse
            # writes; the watchdog promotes us (and only then does the
            # transport start — a standby must never double-dispatch tasks
            # the primary is already delivering).
            from .taskstore.replication import (FailoverWatchdog,
                                                JournalReplicator)
            self.replicator = JournalReplicator(
                self.store, self.config.replicate_from,
                api_key=self.config.replicate_api_key,
                metrics=self.metrics)
            self.replicator.start()
            self.watchdog = FailoverWatchdog(
                self.replicator,
                interval=self.config.failover_interval,
                down_after=self.config.failover_down_after,
                on_promote=self._on_promoted)
            self.watchdog.start()
            await self.depth_logger.start()
            self._started = True
            return
        if hasattr(self.store, "passive_fencing"):
            # A primary with NO configured HA peer must not be demotable by
            # a forged or stale X-Store-Epoch header — there is no standby
            # to take over, so passive fencing evidence would only convert
            # a bogus header into a total write outage. advertise_url is
            # the HA-pair marker (both charts set it); the explicit
            # /demote endpoint stays available either way.
            self.store.passive_fencing = bool(self.config.advertise_url)
        if hasattr(self.store, "start_replication"):
            # Sharded store: per-shard replica journal tails (sharding.py).
            await self.store.start_replication()
        await self._start_transport(loop)
        await self.depth_logger.start()
        if self.reaper is not None:
            await self.reaper.start()
        if self.slo is not None:
            await self.slo.start()
        for scaler in self.autoscalers:
            await scaler.start()
        self._reseed_unfinished()
        self._started = True

    async def _start_transport(self, loop: asyncio.AbstractEventLoop) -> None:
        self._transport_running = True
        if self.config.transport == "push":
            if self.topic is None:
                # A demotion closed the previous topic/webhook; a
                # re-promotion (fail-back) rebuilds them.
                self._build_push()
            await self._start_push(loop)
        else:
            self.broker.bind_loop(loop)

            def on_dead_letter(msg) -> None:
                # Runs on the event loop (queues are loop-bound); fail the
                # task asynchronously so it never sits non-terminal after its
                # message is gone.
                self._spawn_bg(loop, self._fail_dead_letter(msg.task_id))

            self.broker.set_dead_letter_handler(on_dead_letter)
            await self.dispatchers.start()
            if self.pipeline is not None:
                # The coordinator starts WITH the transport (never on a
                # standby — a follower must not drive pipeline runs the
                # primary is already driving) and its entry-queue
                # consumption precedes the restart re-seed, which is the
                # pipeline resume path.
                await self.pipeline.start()

    async def _on_promoted(self) -> None:
        """Watchdog fired: this standby is now the primary. Start transport
        + watchdogs and re-dispatch EVERY unfinished task (they arrived via
        replication, so none has a broker message here) — exactly the
        restart re-seed, with the replicated store as the journal."""
        import logging
        logging.getLogger("ai4e_tpu.platform").warning(
            "promoted to primary; starting transport and re-seeding "
            "%d unfinished tasks", len(self.store.unfinished_tasks()))
        # Release the replicator: the watchdog stopped its loop but the
        # REFERENCE must clear too — demote_now gates auto-rejoin on
        # `replicator is None`, and the /role endpoint's "replicating"
        # field reads the same attribute (a stale object here would make a
        # future fail-back silently skip rejoin). The watchdog reference
        # stays: its run loop returns right after this hook, and its
        # `promoted` event is part of the observable surface.
        if self.replicator is not None:
            await self.replicator.aclose()
            self.replicator = None
        loop = asyncio.get_running_loop()
        await self._start_transport(loop)
        if self.reaper is not None:
            await self.reaper.start()
        if self.slo is not None:
            await self.slo.start()
        for scaler in self.autoscalers:
            await scaler.start()
        publish = (self.topic.publish if self.config.transport == "push"
                   else self.broker.publish)
        for task in self.store.unfinished_tasks():
            publish(task)
        # Actively fence the deposed primary (split-brain closure): keep
        # knocking on its door so it demotes — and rejoins us — the moment
        # the partition heals, even if no client traffic ever reaches it.
        if self.config.replicate_from:
            from .taskstore.replication import FencingProber
            self.prober = FencingProber(
                self.store, self.config.replicate_from,
                advertise_url=self.config.advertise_url,
                api_key=self.config.replicate_api_key,
                interval=self.config.failover_interval)
            self.prober.start()

    async def promote_now(self) -> None:
        """Manual-failover entry (HTTP ``POST /v1/taskstore/promote`` routes
        here via make_app's ``lifecycle``): the same sequence the watchdog
        runs — replication torn down FIRST, so a racing poll can never
        resync-wipe the newly-promoted primary (ADVICE r4 high)."""
        if self.watchdog is not None:
            await self.watchdog.stop()
            self.watchdog = None
        if self.replicator is not None:
            await self.replicator.aclose()
            self.replicator = None
        if getattr(self.store, "role", "primary") == "primary":
            return  # already primary — idempotent
        self.store.promote()
        await self._on_promoted()

    async def demote_now(self, epoch: int, primary_url: str | None = None
                         ) -> None:
        """Fence this node out of the primary role (HTTP ``POST
        /v1/taskstore/demote`` routes here). The store flip is first and
        synchronous — writes refuse before this returns; raises
        ``StaleEpochError`` (handler: 409) when the caller's epoch is not
        newer. Then the primary-side machinery stops, and with
        ``primary_url`` the node rejoins the new primary as a standby —
        watchdog armed, so the pair can fail back."""
        self.store.demote(epoch)
        # Stop the primary-side machinery if it is still running. Keyed on
        # actual transport state, not on the role at call time: a PASSIVE
        # demotion (a client's epoch header flipped the bare store mid-
        # request) leaves the platform's dispatchers running — the prober's
        # follow-up demote call cleans that up here.
        if self._transport_running:
            import logging
            logging.getLogger("ai4e_tpu.platform").warning(
                "demoted at epoch %d (new primary: %s); stopping transport",
                epoch, primary_url or "unknown")
            self._transport_running = False
            if self.prober is not None:
                await self.prober.aclose()
                self.prober = None
            for scaler in self.autoscalers:
                await scaler.stop()
            if self.reaper is not None:
                await self.reaper.stop()
            if self.pipeline is not None:
                # Live runs abandon; the new primary's re-seed republishes
                # their (non-terminal) root tasks and ITS coordinator
                # resumes them — the same path as a restart.
                await self.pipeline.stop()
            if self.dispatchers is not None:
                await self.dispatchers.stop()
            if self.topic is not None:
                # Push transport: in-flight deliveries drain; their result
                # writes hit the store fence (NotPrimaryError → 503) and
                # the new primary's re-seed owns redelivery. aclose is
                # terminal, so drop the topic + webhook — a re-promotion
                # rebuilds them (_start_transport → _build_push).
                await self.topic.aclose()
                self.topic = None
                self.webhook = None
                self.store.set_publisher(None)
                if self._webhook_runner is not None:
                    await self._webhook_runner.cleanup()
                    self._webhook_runner = None
        if primary_url and self.replicator is None:
            from .taskstore.replication import (FailoverWatchdog,
                                                JournalReplicator)
            self.config.replicate_from = primary_url
            self.replicator = JournalReplicator(
                self.store, primary_url,
                api_key=self.config.replicate_api_key,
                metrics=self.metrics)
            self.replicator.start()
            self.watchdog = FailoverWatchdog(
                self.replicator,
                interval=self.config.failover_interval,
                down_after=self.config.failover_down_after,
                on_promote=self._on_promoted)
            self.watchdog.start()

    async def _start_push(self, loop: asyncio.AbstractEventLoop) -> None:
        """Push transport: serve the webhook dispatcher app, then validate
        the topic → webhook subscription (the reference's Event Grid
        subscription handshake, ``deploy_event_grid_subscription.sh``). The
        webhook runs on its own port so the topic→webhook leg is a real HTTP
        hop, exactly as process-separable as the reference's Functions."""
        from aiohttp import web as aioweb
        self.topic.bind_loop(loop)

        def on_dead_letter(event) -> None:
            self._spawn_bg(loop, self._fail_dead_letter(event.id))

        self.topic.set_dead_letter_handler(on_dead_letter)
        runner = aioweb.AppRunner(self.webhook.app)
        await runner.setup()
        site = aioweb.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        port = runner.addresses[0][1]
        self._webhook_runner = runner
        await self.topic.subscribe(
            "backend-webhook", f"http://127.0.0.1:{port}/api/events")

    def _spawn_bg(self, loop: asyncio.AbstractEventLoop, coro) -> asyncio.Task:
        """Spawn background work with a STRONG reference held until done
        (AIL004): the loop's weak ref alone lets the garbage collector kill
        the task mid-flight, silently dropping the terminal transition."""
        task = loop.create_task(coro)
        self._bg_tasks.add(task)
        task.add_done_callback(self._bg_tasks.discard)
        return task

    async def _fail_dead_letter(self, task_id: str) -> None:
        try:
            task = self.store.get(task_id)
            if task.canonical_status not in TaskStatus.TERMINAL:
                await self.task_manager.fail_task(
                    task_id, TaskStatus.DEAD_LETTER)
        except Exception:  # noqa: BLE001 — best-effort terminal transition
            import logging
            logging.getLogger("ai4e_tpu.platform").exception(
                "could not fail dead-lettered task %s", task_id)

    def _reseed_unfinished(self) -> None:
        """Re-enqueue tasks restored from the journal in a non-terminal state
        — the redelivery the reference gets from Service Bus persistence
        (autoComplete:false, BackendQueueProcessor/host.json:7): a crashed
        worker's task is dispatched again on platform restart. Only
        journal-*restored* tasks are re-seeded; tasks created in this process
        already have their broker message."""
        restored = getattr(self.store, "replayed_task_ids", None)
        if not restored:
            return
        publish = (self.topic.publish if self.config.transport == "push"
                   else self.broker.publish)
        for task in self.store.unfinished_tasks():
            if task.task_id in restored:
                publish(task)

    async def stop(self) -> None:
        if self.watchdog is not None:
            await self.watchdog.stop()
            self.watchdog = None
        if self.replicator is not None:
            await self.replicator.aclose()
            self.replicator = None
        if self.prober is not None:
            await self.prober.aclose()
            self.prober = None
        if self._started:
            for scaler in self.autoscalers:
                await scaler.stop()
            if self.pipeline is not None:
                await self.pipeline.stop()
            if self.dispatchers is not None:
                await self.dispatchers.stop()
            if self.reaper is not None:
                await self.reaper.stop()
            if self.slo is not None:
                await self.slo.stop()
            await self.depth_logger.stop()
            if hasattr(self.store, "stop_replication"):
                await self.store.stop_replication()
            self._started = False
        for svc in self.services:
            await svc.drain(timeout=5.0)
        # Transport teardown AFTER service drain: a draining async task may
        # still hand off a pipeline stage, which must publish — the queue
        # broker stays open until here too. (Push cleanup also runs when
        # start() failed mid-way, e.g. a handshake error after the webhook
        # site was bound.)
        if self.topic is not None:
            await self.topic.aclose()
        if self._webhook_runner is not None:
            await self._webhook_runner.cleanup()
            self._webhook_runner = None
        if self.broker is not None and hasattr(self.broker, "close"):
            self.broker.close()
