"""The rollout controller — SLO-burn-guarded canary promotion/rollback.

Upgrades a fleet one worker at a time with zero client-visible loss:
drain the worker (``drain.py``), restart it at the new generation, step
the canary traffic share up the configured ladder — holding each step
for a clean fast+slow burn window (the multi-window multi-burn shape the
SLO engine exports, ``observability/slo.py``) — and automatically roll
back (re-weight to the old generation, drain + revert the upgraded
replicas via the existing reload/restart path) when the canary
generation's burn rate or breaker state breaches. Every transition
stamps ``rollout``/``rollback`` evidence into the hop ledger through the
fleet adapter, so the trace CLI renders the upgrade like any other
timeline (docs/observability.md).

The controller is transport-agnostic: a ``fleet`` adapter supplies the
verbs (drain/upgrade/revert/weights/burn). The rig's adapter drives real
OS processes over HTTP (``rig/rollout.py``); tests drive an in-memory
fleet with an injected clock.
"""

from __future__ import annotations

import asyncio
import logging
import math
import time
from dataclasses import dataclass, field

log = logging.getLogger("ai4e_tpu.rollout")


def parse_steps(spec: str) -> list[float]:
    """``"5,25,50,100"`` → monotonically increasing percent ladder ending
    at 100 (a rollout that never reaches 100% would strand the fleet
    split across generations)."""
    steps: list[float] = []
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        value = float(part)
        if not (0.0 < value <= 100.0):
            raise ValueError(
                f"canary step {part!r} must be in (0, 100] percent")
        if steps and value <= steps[-1]:
            raise ValueError(
                f"canary steps must increase: {part!r} after {steps[-1]}")
        steps.append(value)
    if not steps:
        raise ValueError("canary step ladder is empty")
    if steps[-1] != 100.0:
        raise ValueError("canary step ladder must end at 100")
    return steps


@dataclass
class RolloutPolicy:
    """Knob set mirrored by ``AI4E_ROLLOUT_*`` (docs/config.md)."""

    drain_timeout_ms: float = 30000.0   # per-worker drain budget
    canary_steps: str = "25,50,100"     # percent ladder (parse_steps)
    step_hold_s: float = 10.0           # clean-burn window per step
    guard_tick_s: float = 1.0           # burn sampling period in the hold
    burn_fast_max: float = 1.0          # fast-window burn bar
    burn_slow_max: float = 1.0          # slow-window burn bar
    drain_eject_ttl_s: float = 30.0     # placement eject TTL per drain mark

    @property
    def steps(self) -> list[float]:
        return parse_steps(self.canary_steps)

    @classmethod
    def from_config(cls, section) -> "RolloutPolicy":
        """Build from ``FrameworkConfig().rollout`` (config.py
        RolloutSection — the AI4E_ROLLOUT_* env surface)."""
        return cls(drain_timeout_ms=section.drain_timeout_ms,
                   canary_steps=section.canary_steps,
                   step_hold_s=section.step_hold_s,
                   guard_tick_s=section.guard_tick_s,
                   burn_fast_max=section.burn_fast_max,
                   burn_slow_max=section.burn_slow_max,
                   drain_eject_ttl_s=section.drain_eject_ttl_s)


@dataclass
class RolloutResult:
    outcome: str                        # "promoted" | "rolled_back"
    generation: int
    reason: str = ""
    upgraded: list = field(default_factory=list)
    reverted: list = field(default_factory=list)
    weight_history: list = field(default_factory=list)


class RolloutController:
    """One rollout of ``fleet`` from its current generation to
    ``generation``. The ``fleet`` adapter duck-types:

    - ``workers() -> list[str]``                 stable worker ids
    - ``await drain(worker) -> bool``            drain verb (bounded)
    - ``await upgrade(worker, generation)``      restart at generation
    - ``await revert(worker, generation)``       restart back (rollback)
    - ``await wait_healthy(worker) -> bool``     post-restart readiness
    - ``await set_split(generation, share)``     canary weight (0..1)
    - ``await burn(generation) -> {"fast": f, "slow": s}``
    - ``breaker_open(generation) -> bool``       canary breaker state
    - ``await stamp(event, reason)``             hop-ledger evidence
    """

    def __init__(self, fleet, generation: int,
                 old_generation: int | None = None,
                 policy: RolloutPolicy | None = None,
                 clock=time.monotonic):
        self.fleet = fleet
        self.generation = int(generation)
        self.old_generation = (int(old_generation)
                               if old_generation is not None
                               else self.generation - 1)
        self.policy = policy or RolloutPolicy()
        self._clock = clock

    async def run(self) -> RolloutResult:
        from ..observability.ledger import ROLLOUT
        policy = self.policy
        workers = list(self.fleet.workers())
        result = RolloutResult(outcome="promoted", generation=self.generation)
        await self.fleet.stamp(
            ROLLOUT, f"generation {self.generation} begin "
                     f"({len(workers)} workers, steps {policy.canary_steps})")
        for share_pct in policy.steps:
            # Upgrade enough workers — one at a time, drain first — that
            # the new generation can actually carry this step's share.
            target = max(1, math.ceil(share_pct / 100.0 * len(workers)))
            while len(result.upgraded) < target:
                worker = workers[len(result.upgraded)]
                clean = await self.fleet.drain(worker)
                await self.fleet.upgrade(worker, self.generation)
                healthy = await self.fleet.wait_healthy(worker)
                result.upgraded.append(worker)
                await self.fleet.stamp(
                    ROLLOUT,
                    f"{worker} -> generation {self.generation}"
                    + ("" if clean else " (drain timed out; stragglers "
                                        "redelivered)"))
                if not healthy:
                    await self._rollback(
                        result, f"{worker} unhealthy after upgrade")
                    return result
            await self.fleet.set_split(self.generation, share_pct / 100.0)
            result.weight_history.append(share_pct)
            await self.fleet.stamp(
                ROLLOUT, f"canary weight {share_pct:g}%")
            breach = await self._guard(policy.step_hold_s)
            if breach:
                await self._rollback(result, breach)
                return result
        await self.fleet.stamp(
            ROLLOUT, f"generation {self.generation} promoted")
        return result

    async def _guard(self, hold_s: float) -> str | None:
        """Hold the current weight for ``hold_s``, sampling the canary
        generation's burn + breaker state each tick; returns the breach
        reason, or None after a clean window."""
        policy = self.policy
        deadline = self._clock() + max(0.0, hold_s)
        while True:
            if self.fleet.breaker_open(self.generation):
                return "canary breaker open"
            burns = await self.fleet.burn(self.generation)
            fast = float(burns.get("fast", 0.0))
            slow = float(burns.get("slow", 0.0))
            # The multi-window shape: page (here: roll back) only when
            # BOTH windows burn — a blip doesn't roll back, a slow leak
            # doesn't hide (observability/slo.py).
            if fast > policy.burn_fast_max and slow > policy.burn_slow_max:
                return (f"canary burn fast={fast:.2f} slow={slow:.2f} "
                        f"over {policy.burn_fast_max:g}/"
                        f"{policy.burn_slow_max:g}")
            if self._clock() >= deadline:
                return None
            await asyncio.sleep(policy.guard_tick_s)

    async def _rollback(self, result: RolloutResult, reason: str) -> None:
        """Re-weight to the old generation, then drain + revert every
        upgraded replica via the existing restart/reload path."""
        from ..observability.ledger import ROLLBACK
        result.outcome, result.reason = "rolled_back", reason
        log.warning("rollout of generation %d rolling back: %s",
                    self.generation, reason)
        await self.fleet.set_split(self.generation, 0.0)
        await self.fleet.stamp(ROLLBACK, reason)
        for worker in list(result.upgraded):
            await self.fleet.drain(worker)
            await self.fleet.revert(worker, self.old_generation)
            await self.fleet.wait_healthy(worker)
            result.reverted.append(worker)
            await self.fleet.stamp(
                ROLLBACK, f"{worker} -> generation {self.old_generation}")
        await self.fleet.stamp(
            ROLLBACK, f"generation {self.generation} rolled back ({reason})")
