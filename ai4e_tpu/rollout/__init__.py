"""Zero-downtime rollouts — drain-aware workers, SLO-burn-guarded canary,
automatic rollback (docs/deployment.md#rollouts).

The reference platform's deploy story is Istio/Helm rolling upgrades of
containerized model APIs; our native rebuild had every ingredient
(per-version servables, the SLO burn engine, breakers, the multi-process
rig) but no upgrade lifecycle — a weight rollout was either an
instantaneous per-worker hot swap or SIGTERM-the-group. This package is
the missing lifecycle, three pieces:

- ``drain``     — the worker-side graceful-drain state machine: stop
  admitting, finish in-flight device work bounded by a budget, redeliver
  stragglers through the broker per task (stdlib-only so the race
  explorer exercises the REAL code, like ``runtime/decode.py``);
- ``canary``    — generation-keyed traffic splitting applied on top of
  the weighted in-tier pick every placement path already uses;
- ``controller``— the rollout controller: upgrade one worker at a time,
  step the canary weight up on clean fast+slow SLO burn windows, and
  automatically roll back when the canary generation's burn rate or
  breaker state breaches.
"""

from .canary import CanaryWeights, generation_label
from .controller import RolloutController, RolloutPolicy
from .drain import (DRAINING_HEADER, DrainingError, DrainState,
                    drain_worker, retire_pending)

__all__ = [
    "CanaryWeights",
    "generation_label",
    "RolloutController",
    "RolloutPolicy",
    "DRAINING_HEADER",
    "DrainingError",
    "DrainState",
    "drain_worker",
    "retire_pending",
]
