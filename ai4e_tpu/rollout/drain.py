"""Graceful drain — the worker-side rollout state machine.

A draining worker must (docs/deployment.md#drain):

1. stop admitting: new submits raise ``DrainingError`` and the HTTP
   surface answers 503 + ``Retry-After`` + ``X-Draining`` (saturation-
   neutral for breakers — draining is an eject-from-placement signal,
   never a failure);
2. retire every UNCUT pending example immediately (the broker redelivers
   each task to a peer — the PR 17 poisoned-row path), while batches
   already cut to the device finish normally;
3. wait — bounded by ``AI4E_ROLLOUT_DRAIN_TIMEOUT_MS`` — for in-flight
   device work AND any in-flight hot reload to complete; stragglers past
   the budget are force-retired and redeliver per task too.

Stdlib-only on purpose: the CI race-smoke job (no JAX, no numpy)
explores the drain-flip windows against THIS code, the same contract
``runtime/decode.py`` keeps (docs/concurrency.md).
"""

from __future__ import annotations

import asyncio
import time

# Refusal marker for a draining worker's 503s: dispatchers that observe
# it eject the backend from placement for a TTL (resilience/health.py
# ``mark_draining``) instead of hammering a worker that told them it is
# leaving. Deliberately distinct from X-Not-Primary (a rotate marker)
# and X-Shed-Reason (an overload marker): draining is neither.
DRAINING_HEADER = "X-Draining"

ACTIVE = "active"
DRAINING = "draining"
DRAINED = "drained"

_STATE_CODES = {ACTIVE: 0, DRAINING: 1, DRAINED: 2}


class DrainingError(Exception):
    """A submit was refused — or a pending entry retired — because the
    worker is draining. The async path redelivers the task through the
    broker (per task, like a poisoned row); the sync path answers 503 +
    Retry-After so the caller's proxy retries a peer."""


class DrainState:
    """The drain lifecycle shared by every surface of one worker process:
    the batcher(s), the decode engines, the reload endpoint, and the
    admission checks all consult ONE of these.

    Two suspension-point-atomicity contracts (docs/concurrency.md) live
    here, both with ``explore_interleavings`` regressions:

    - ``begin()`` is synchronous: the flip and the moment new submits
      start refusing are one event-loop step — there is no window where
      a submit admitted "before" the flip lands in a pending queue the
      drain already swept;
    - ``try_begin_reload()`` checks the drain state AND registers the
      reload with no await between: a reload racing a drain either
      lands fully before the drain (which then waits for it) or is
      refused with 409 — a weight swap can never complete on a worker
      that already reported itself drained.
    """

    def __init__(self, clock=time.monotonic):
        self._state = ACTIVE
        self._reloads = 0
        self._clock = clock
        self.began_at = 0.0

    # -- state --------------------------------------------------------------

    @property
    def state(self) -> str:
        return self._state

    @property
    def state_code(self) -> int:
        return _STATE_CODES[self._state]

    @property
    def is_draining(self) -> bool:
        """True from the drain flip on (draining OR drained) — every
        admission/refusal surface keys on this."""
        return self._state != ACTIVE

    def begin(self) -> bool:
        """Flip into draining; False when already past active (the verb
        is idempotent — a second POST reports state, it does not restart
        the drain)."""
        if self._state != ACTIVE:
            return False
        self._state = DRAINING
        self.began_at = self._clock()
        return True

    def mark_drained(self) -> None:
        if self._state == DRAINING:
            self._state = DRAINED

    def resume(self) -> None:
        """Back to serving — the rollback path re-arms a worker whose
        drain was aborted (re-weighted to the old generation) without a
        process restart."""
        self._state = ACTIVE
        self.began_at = 0.0

    # -- reload interlock ----------------------------------------------------

    @property
    def reloads_in_flight(self) -> int:
        return self._reloads

    def try_begin_reload(self) -> bool:
        """Admit a hot reload unless draining. Check + register are one
        synchronous step (no await): the drain's completion wait reads
        ``reloads_in_flight`` and must never see 0 while a reload that
        passed the check is still swapping weights."""
        if self._state != ACTIVE:
            return False
        self._reloads += 1
        return True

    def end_reload(self) -> None:
        self._reloads = max(0, self._reloads - 1)


def retire_pending(pending_by_model: dict, exc_factory=DrainingError) -> int:
    """Fail every uncut pending future with ``exc_factory()`` and clear
    the queues IN PLACE — the flusher and this retire see the same list
    objects, so the take-and-clear must be one synchronous step (no
    await between reading a queue and emptying it): an interleaved batch
    cut would otherwise deliver a device result into a future this
    sweep already failed. Futures the cut already resolved are skipped
    (``done()``), never double-resolved. Returns the retire count."""
    retired = 0
    for entries in list(pending_by_model.values()):
        taken, entries[:] = list(entries), []
        for entry in taken:
            fut = getattr(entry, "future", entry)
            if not fut.done():
                fut.set_exception(exc_factory())
                retired += 1
    return retired


async def drain_worker(state: DrainState, batchers=(), engines=(),
                       timeout_s: float = 30.0, poll_s: float = 0.05,
                       clock=time.monotonic) -> dict:
    """The drain verb's body: flip the state, retire uncut work, wait —
    bounded — for in-flight device batches, active decode sequences and
    any in-flight reload, then force-retire stragglers (each redelivers
    through the broker per task, handled by the callers awaiting their
    futures). Idempotent: a second call while draining just waits on the
    same condition.

    ``batchers``/``engines`` duck-type ``begin_drain() -> int``,
    ``drain_complete: bool`` and (engines only) ``force_drain() -> int``.
    """
    state.begin()
    retired = 0
    for b in batchers:
        retired += b.begin_drain()
    for e in engines:
        retired += e.begin_drain()
    deadline = clock() + max(0.0, timeout_s)
    while clock() < deadline:
        if (state.reloads_in_flight == 0
                and all(b.drain_complete for b in batchers)
                and all(e.drain_complete for e in engines)):
            break
        await asyncio.sleep(poll_s)
    forced = 0
    for e in engines:
        forced += e.force_drain()
    complete = (state.reloads_in_flight == 0
                and all(b.drain_complete for b in batchers)
                and all(e.drain_complete for e in engines))
    state.mark_drained()
    return {"state": state.state, "retired": retired, "forced": forced,
            "clean": complete,
            "drain_s": round(clock() - state.began_at, 3)}
