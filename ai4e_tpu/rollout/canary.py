"""Versioned canary routing — generation-keyed traffic splitting.

The rollout generation is tracked in the registry beside
``params_version`` (``ServableModel.generation``); placement splits
traffic by weight between old- and new-generation replicas by rescaling
the weighted backend set every pick already consumes
(``utils/backends.pick_backend`` — "equal-cost backends are a canary
split"). The split is exact by construction: the canary generation's
backends are rescaled to hold ``share`` of the pool's total weight as a
GROUP, whatever the replica counts are on each side.

``generation_label`` is the bounded-cardinality mapper for the
``generation`` metric dimension (AIL013, docs/observability.md): a
long-lived worker that reloads weekly would otherwise mint one series
per generation number forever.
"""

from __future__ import annotations

#: Distinct generation values one process may label before folding the
#: rest into ``other`` — a worker sees its own generation plus a handful
#: of rollouts per process lifetime, so the cap is generous.
GENERATION_LABEL_CAP = 8
_seen_generations: list[str] = []


def generation_label(generation) -> str:
    """Bounded mapper for the ``generation`` metric label: the first
    ``GENERATION_LABEL_CAP`` distinct values seen by this process keep
    their own series; everything after folds into ``other`` (the
    tenancy top-N+other precedent, docs/tenancy.md)."""
    value = str(generation)
    if value in _seen_generations:
        return value
    if len(_seen_generations) < GENERATION_LABEL_CAP:
        _seen_generations.append(value)
        return value
    return "other"


class CanaryWeights:
    """Generation→traffic-share policy applied to a weighted backend set.

    One instance per assembly, attached to the shared ``BackendHealth``
    (and through it the orchestrator): both placement paths then split
    in-tier traffic between generations without either learning anything
    about rollouts. ``apply`` is pure with respect to the pool — callers
    keep their own lists."""

    def __init__(self):
        self._generations: dict[str, int] = {}
        self._canary_generation: int | None = None
        self._canary_share: float = 0.0

    # -- registration --------------------------------------------------------

    def set_generation(self, uri: str, generation: int) -> None:
        self._generations[str(uri)] = int(generation)

    def generation_of(self, uri: str) -> int | None:
        return self._generations.get(str(uri))

    def set_split(self, canary_generation: int, share: float) -> None:
        """Route ``share`` (0..1) of the pool's traffic to backends of
        ``canary_generation``; the rest serves the other generations."""
        self._canary_generation = int(canary_generation)
        self._canary_share = min(1.0, max(0.0, float(share)))

    def clear_split(self) -> None:
        self._canary_generation = None
        self._canary_share = 0.0

    @property
    def split(self) -> tuple[int | None, float]:
        return self._canary_generation, self._canary_share

    # -- placement hook ------------------------------------------------------

    def apply(self, pool):
        """Rescale ``[(uri, weight), ...]`` so the canary generation's
        backends hold exactly the configured share of total weight.
        Degenerate pools pass through unchanged: no split configured,
        no canary backend present (nothing to canary), or no
        non-canary backend present (the canary IS the fleet)."""
        if self._canary_generation is None or not pool:
            return pool
        canary_total = other_total = 0.0
        for uri, weight in pool:
            if self._generations.get(uri) == self._canary_generation:
                canary_total += weight
            else:
                other_total += weight
        if canary_total <= 0 or other_total <= 0:
            return pool
        total = canary_total + other_total
        share = self._canary_share
        out = []
        for uri, weight in pool:
            if self._generations.get(uri) == self._canary_generation:
                out.append((uri, weight * share * total / canary_total))
            else:
                out.append((uri, weight * (1.0 - share) * total
                            / other_total))
        if all(w <= 0 for _, w in out):
            return pool
        return out
