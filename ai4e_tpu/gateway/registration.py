"""API registration — publishing a model API onto the platform edge.

The reference registers an API with ~250 lines of az-CLI: policy templates
filled by ``api_management_customizer.py`` (backend URL splicing at
``api_management_customizer.py:4-44``) and ``az rest`` PUTs creating the API,
its operations, and per-operation policies
(``APIManagement/create_sync_api_management_api.sh:38-92``,
``create_async_api_management_api.sh:52-80``). Here the same act is a typed
``ApiDefinition`` rendered into gateway routes — declarative registration
replacing imperative deployment.

The public URL shape is the reference's ``/{version}/{organization}/{api}``
(the pipeline hand-off builds exactly that shape,
``distributed_api_task.py:74-75``), with operations as path tails under it
(the landcover example registers ``classify/classifybyextent/tile/
tilebyextent`` ops under one API, ``create_sync_api_management_api.sh:38-92``)
— tails ride the gateway/dispatcher tail-grafting, so operations need no
individual registration.
"""

from __future__ import annotations

import json
from dataclasses import dataclass


@dataclass
class ApiDefinition:
    """One published API: who owns it, what it's called, where it runs."""

    organization: str            # e.g. "camera-trap"
    api: str                     # e.g. "detection"
    backend_host: str            # worker base, e.g. "http://worker:8081"
    version: str = "v1"
    mode: str = "async"          # "sync" | "async"
    operations: tuple = ()       # documented op tails (informational)
    backend_path: str = ""       # path on the worker; default /{version}/{api}
    # queue-transport dispatch knobs (publish_async_api passthrough)
    concurrency: int | None = None
    retry_delay: float | None = None
    autoscale: dict | None = None

    @property
    def public_prefix(self) -> str:
        return f"/{self.version}/{self.organization}/{self.api}"

    @property
    def backend_uri(self) -> str:
        path = self.backend_path or f"/{self.version}/{self.api}"
        return self.backend_host.rstrip("/") + path

    @classmethod
    def from_dict(cls, rec: dict) -> "ApiDefinition":
        rec = dict(rec)
        if "operations" in rec:
            rec["operations"] = tuple(rec["operations"])
        return cls(**rec)


def routes_from_definitions(defs: list[ApiDefinition]) -> dict:
    """Render definitions to the control plane's ``routes.json`` shape —
    the customizer step: templates + concrete addresses → deployable spec
    (``api_management_customizer.py:13-30`` splices the ingress IP the same
    way)."""
    apis = []
    for d in defs:
        entry: dict = {"prefix": d.public_prefix, "backend": d.backend_uri,
                       "mode": d.mode}
        if d.concurrency is not None:
            entry["concurrency"] = d.concurrency
        if d.retry_delay is not None:
            entry["retry_delay"] = d.retry_delay
        if d.autoscale is not None:
            entry["autoscale"] = d.autoscale
        apis.append(entry)
    return {"apis": apis}


def register_definitions(platform, defs: list[ApiDefinition]) -> None:
    """Publish definitions directly onto a ``LocalPlatform`` — the in-process
    equivalent of running the registration scripts against APIM."""
    for d in defs:
        if d.mode == "sync":
            platform.publish_sync_api(d.public_prefix, d.backend_uri)
            continue
        autoscale = None
        if d.autoscale is not None:
            from ..scaling import AutoscalePolicy
            autoscale = AutoscalePolicy(**d.autoscale)
        platform.publish_async_api(
            d.public_prefix, d.backend_uri,
            retry_delay=d.retry_delay, concurrency=d.concurrency,
            autoscale=autoscale)


def load_definitions(path: str) -> list[ApiDefinition]:
    """Load an ``apis.json``: ``{"apis": [{organization, api, backend_host,
    ...}, ...]}``."""
    with open(path, encoding="utf-8") as f:
        spec = json.load(f)
    return [ApiDefinition.from_dict(rec) for rec in spec.get("apis", [])]
