"""Per-subscription-key rate limiting — the APIM product-throttling slot.

The reference publishes its APIs behind Azure API Management subscriptions;
APIM products carry request-rate throttling per subscription key alongside
the key auth itself. The gateway here had the auth
(``gateway/router.py`` subscription-key middleware) but any valid key got
unlimited rate. This module is the missing throttle: a token bucket per key,
refilled continuously, answering 429 + ``Retry-After`` when drained — the
same contract the platform's own backpressure uses everywhere else
(dispatcher 429 handling, ``BackendQueueProcessor.cs:54-64``).
"""

from __future__ import annotations

import time
from dataclasses import dataclass


@dataclass
class RateLimit:
    """``rps`` sustained requests/second; ``burst`` bucket capacity (how far
    above the sustained rate a key may spike)."""

    rps: float
    burst: float = 0.0

    def __post_init__(self):
        if self.rps <= 0:
            raise ValueError(f"rps must be positive, got {self.rps}")
        if self.burst <= 0:
            self.burst = max(2.0 * self.rps, 1.0)


class RateLimiter:
    """Token buckets keyed by subscription key (or any caller identity).

    Single-threaded by design: the gateway's middleware calls ``allow`` on
    the event loop with no awaits in between, so no lock is needed. Buckets
    are created lazily per key and pruned when idle long enough to be full
    again (bounded memory under key churn).
    """

    def __init__(self, default: RateLimit,
                 per_key: dict[str, RateLimit] | None = None,
                 clock=time.monotonic):
        self.default = default
        self.per_key = dict(per_key or {})
        self._clock = clock
        # key -> [tokens, last_refill_ts]
        self._buckets: dict[str, list[float]] = {}
        self._last_prune = clock()

    def limit_for(self, key: str) -> RateLimit:
        return self.per_key.get(key, self.default)

    def allow(self, key: str) -> tuple[bool, float]:
        """Take one token from ``key``'s bucket. Returns ``(allowed,
        retry_after_seconds)`` — ``retry_after`` is 0 when allowed, else the
        time until one token accrues (the ``Retry-After`` header value)."""
        limit = self.limit_for(key)
        now = self._clock()
        if now - self._last_prune > 60.0:
            self._prune(now)
        bucket = self._buckets.get(key)
        if bucket is None:
            bucket = self._buckets[key] = [limit.burst, now]
        tokens, last = bucket
        tokens = min(limit.burst, tokens + (now - last) * limit.rps)
        if tokens >= 1.0:
            bucket[0] = tokens - 1.0
            bucket[1] = now
            return True, 0.0
        bucket[0] = tokens
        bucket[1] = now
        return False, (1.0 - tokens) / limit.rps

    def _prune(self, now: float) -> None:
        """Drop buckets idle long enough to be full — indistinguishable from
        fresh ones, so dropping them changes nothing but memory."""
        self._last_prune = now
        full_after = {key: (self.limit_for(key).burst
                            / self.limit_for(key).rps)
                      for key in self._buckets}
        self._buckets = {
            key: bucket for key, bucket in self._buckets.items()
            if now - bucket[1] < full_after[key]}


@dataclass
class Quota:
    """``requests`` allowed per ``window_seconds`` — the APIM product
    *quota* (longer-horizon cap) beside the rate throttle (short-horizon
    smoothing). APIM renews quotas on fixed calendar windows; the fixed
    rolling-start window here is the standard approximation."""

    requests: int
    window_seconds: float = 3600.0

    def __post_init__(self):
        if self.requests <= 0:
            raise ValueError(f"quota must be positive, got {self.requests}")
        if self.window_seconds <= 0:
            raise ValueError(
                f"quota window must be positive, got {self.window_seconds}")


class QuotaTracker:
    """Fixed-window request counters keyed by subscription key.

    Same single-threaded contract as ``RateLimiter`` (called on the event
    loop, no awaits in between). ``allow`` returns ``(allowed,
    retry_after_seconds)`` — on exhaustion ``retry_after`` is the time to
    the window's reset (APIM answers 403 for quota vs 429 for rate; the
    gateway maps accordingly)."""

    def __init__(self, default: Quota | None,
                 per_key: dict[str, Quota] | None = None,
                 clock=time.monotonic):
        # default None = keys without a per-key quota are unlimited AND
        # untracked (no per-identity window entry — matters when the
        # identity is a client IP).
        self.default = default
        self.per_key = dict(per_key or {})
        self._clock = clock
        # key -> [count, window_start_ts]
        self._windows: dict[str, list[float]] = {}
        self._last_prune = clock()

    def quota_for(self, key: str) -> Quota | None:
        return self.per_key.get(key, self.default)

    def _window(self, key: str, quota: Quota, now: float) -> list[float]:
        if now - self._last_prune > 300.0:
            self._prune(now)
        window = self._windows.get(key)
        if window is None or now - window[1] >= quota.window_seconds:
            window = self._windows[key] = [0.0, now]
        return window

    def would_allow(self, key: str) -> tuple[bool, float]:
        """Non-consuming peek — lets the gateway refuse on quota BEFORE
        taking a rate-limiter token (a quota-403'd request must not burn
        rate tokens, or exhausted clients see short 429 Retry-Afters
        instead of the 403's window-reset backoff)."""
        quota = self.quota_for(key)
        if quota is None:
            return True, 0.0
        now = self._clock()
        window = self._window(key, quota, now)
        if window[0] < quota.requests:
            return True, 0.0
        return False, quota.window_seconds - (now - window[1])

    def allow(self, key: str) -> tuple[bool, float]:
        quota = self.quota_for(key)
        if quota is None:
            return True, 0.0
        now = self._clock()
        window = self._window(key, quota, now)
        if window[0] < quota.requests:
            window[0] += 1.0
            return True, 0.0
        return False, quota.window_seconds - (now - window[1])

    def _prune(self, now: float) -> None:
        """Drop expired windows — a fresh one is created on next use."""
        self._last_prune = now
        self._windows = {
            key: w for key, w in self._windows.items()
            if (q := self.quota_for(key)) is not None
            and now - w[1] < q.window_seconds}


def parse_quota(spec: str) -> Quota:
    """``"N/seconds"`` or bare ``"N"`` (hour window)."""
    n, _, window = (spec or "").strip().partition("/")
    try:
        return Quota(requests=int(n),
                     window_seconds=float(window) if window else 3600.0)
    except ValueError:
        raise ValueError(
            f"bad quota spec {spec!r}; expected N[/window_seconds]") from None


def parse_quotas(spec: str) -> dict[str, Quota]:
    """Per-key overrides: ``key=N[/seconds],...``
    (e.g. ``"partner-key=100000/86400,free-tier=100"``)."""
    out: dict[str, Quota] = {}
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        key, _, q = part.partition("=")
        if not key or not q:
            raise ValueError(f"bad quota entry {part!r}; "
                             "expected key=N[/window_seconds]")
        out[key.strip()] = parse_quota(q)
    return out


def parse_rate_limits(spec: str) -> dict[str, RateLimit]:
    """Parse per-key overrides from config: ``key=rps[:burst],...``
    (e.g. ``"partner-key=50:100,free-tier=2"``)."""
    out: dict[str, RateLimit] = {}
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        key, _, rate = part.partition("=")
        if not key or not rate:
            raise ValueError(f"bad rate-limit entry {part!r}; "
                             "expected key=rps[:burst]")
        rps, _, burst = rate.partition(":")
        out[key.strip()] = RateLimit(rps=float(rps),
                                     burst=float(burst) if burst else 0.0)
    return out
