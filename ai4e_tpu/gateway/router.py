"""Gateway — the platform's front door.

Re-design of the reference's Azure API Management layer (L1). The APIM inbound
policy for an async API builds a task record at the edge and returns the
TaskId synchronously while the transport delivers the work
(``APIManagement/request_policy.xml:3-36``); sync APIs pass straight through to
the cluster ingress (``request_backend_policy.xml:1-16``); task polling hits
the store (``task_management_policy.xml:1-18``). Here those three policies are
one aiohttp app with a programmatic route table instead of az-CLI-deployed XML
(``APIManagement/create_async_api_management_api.sh:52-80``).

Routes:
- ``POST {route.prefix}/…``  (async) → upsert task {Status: created, Endpoint,
  Body, publish: True} → broker; respond 200 with the task JSON immediately;
- ``ANY  {route.prefix}/…``  (sync)  → reverse-proxy to the backend;
- ``GET  /v1/taskmanagement/task/{taskId}`` → task record (404 unknown);
- ``GET  /metrics``, ``GET /healthz``.
"""

from __future__ import annotations

import asyncio
import inspect
import logging
import math
import time
from dataclasses import dataclass

import aiohttp
from aiohttp import web

from ..admission.deadline import (SHED_REASON_HEADER, expired,
                                  parse_deadline_at, parse_priority,
                                  propagation_headers, shed_reason)
from ..observability.ledger import ADMITTED, PUBLISHED, ledger_event
from ..metrics import DEFAULT_REGISTRY, MetricsRegistry
from ..rescache.keys import (CACHE_STATUS_HEADER, cache_bypass_requested,
                             request_key)
from ..utils.backends import normalize_backends, pick_backend
from ..taskstore import (APITask, InMemoryTaskStore, TaskNotFound, TaskStatus,
                         endpoint_path)
from ..utils.http import SessionHolder

log = logging.getLogger("ai4e_tpu.gateway")


async def _aresult(value):
    """Await ``value`` when the store verb came from a remote/async client
    (the rig's ring-routed wire store — ``ai4e_tpu/rig/wire.py``), pass it
    through when it came from the in-process sync store. The gateway's
    store touchpoints all route through this so one Gateway class serves
    both deployments; the sync store pays one ``isawaitable`` check."""
    if inspect.isawaitable(value):
        return await value
    return value


@dataclass
class Route:
    """One published API. ``prefix`` is the public path; async routes create
    tasks, sync routes proxy to ``backend_uri`` (VirtualService rewrite
    semantics, ``APIs/Charts/templates/routing.yml:1-28``)."""

    prefix: str
    mode: str  # "sync" | "async"
    backend_uri: str = ""  # sync: proxy target; async: recorded task endpoint
    # Weighted backend set for sync routes (canary; utils/backends.py);
    # [(backend_uri, 1.0)] for the plain single-backend case.
    backends: list = None
    # None = use the gateway's cap at request time; 0 = explicitly unlimited.
    max_body_bytes: int | None = None
    # Whether the result cache may serve/fill this route. False on weighted
    # canary routes: the cache key hashes the shared endpoint path, not the
    # chosen backend, so one backend's answer would be replayed to ALL of the
    # split's traffic — mixing model versions and starving the canary's
    # evaluation counters. Canary routes always execute (docs/rescache.md).
    cacheable: bool = True


class Gateway:
    def __init__(self, store: InMemoryTaskStore,
                 metrics: MetricsRegistry | None = None,
                 api_keys: set[str] | None = None,
                 max_body_bytes: int = 128 * 1024 * 1024):
        # Edge payload cap (the reference enforces limits at APIM, before
        # anything is stored): an async POST over the limit is refused with
        # 413 BEFORE a task (and its journaled ORIG body) is created;
        # per-route overrides via add_*_route(max_body_bytes=...).
        self.max_body_bytes = max_body_bytes
        self.store = store
        self.metrics = metrics or DEFAULT_REGISTRY
        self.routes: list[Route] = []
        self._requests = self.metrics.counter(
            "ai4e_gateway_requests_total", "Gateway requests by route/outcome")
        # Component tracer carrying THIS gateway's registry: its
        # ai4e_span_seconds series must land beside the gateway counters in
        # the assembly's /metrics, not in the process default (AIL002 —
        # exporter/sampling still follow configure_tracer live).
        from ..observability import Tracer
        self.tracer = Tracer("gateway", metrics=self.metrics)
        # Proxy fan-out is bounded by inbound connections, not the pool.
        self._sessions = SessionHolder(limit=0)
        # Long-poll wake path (_feed_for): a store with per-shard change
        # feeds (the sharded facade, the rig's wire store) supplies them;
        # any other store gets ONE gateway-side feed lazily attached to
        # its listener surface. There is no parallel per-task waiter map
        # any more — the feed is the single wake mechanism, and it wakes
        # with the terminal record itself.
        self._fallback_feed = None
        # Subscription-key auth (the reference's APIM front door requires
        # Ocp-Apim-Subscription-Key on every published API). None → open.
        self._api_keys = set(api_keys) if api_keys else None
        # Per-key rate limiting (APIM product throttling); None → unlimited.
        self._rate_limiter = None
        # Per-key request quotas (APIM product quota); None → unlimited.
        self._quota_tracker = None
        # Multi-tenancy facade (``tenancy/``); None → no tenant resolution,
        # no per-tenant quota, tasks stay tenantless — the pre-tenancy
        # gateway byte for byte. Set via set_tenancy (assembly wires it).
        self._tenancy = None
        # Inference result cache (``rescache/``); None → every request
        # executes. Set via set_result_cache (platform assembly wires it).
        self._result_cache = None
        # Admission controller (``admission/``); None → no deadlines, no
        # shedding, unbounded sync proxy — the pre-admission behavior,
        # untouched. Set via set_admission (platform assembly wires it).
        self._admission = None
        # Per-backend health model (``resilience/``), shared with the
        # dispatchers; None → single-attempt proxying, 502 on the first
        # connection error — the pre-resilience behavior, untouched. Set
        # via set_resilience (platform assembly wires it).
        self._resilience = None
        self._sync_retry_budget = None
        # Orchestrator (``orchestration/``), shared with the dispatchers;
        # None → health-weighted picks and no brownout modes — the
        # pre-orchestration behavior, untouched. Set via
        # set_orchestration (platform assembly wires it).
        self._orchestration = None
        # Request-observability hub (``observability/hub.py``); None →
        # no hop-ledger stamps, no flight recorder, no per-route e2e
        # telemetry — the pre-observability gateway byte for byte. Set
        # via set_observability (platform assembly wires it).
        self._observability = None
        # Task event hub (``pipeline/events.py``); None → no streaming
        # surface, no /events route — the pre-pipeline gateway byte for
        # byte. Set via set_event_stream (platform assembly wires it).
        self._event_hub = None
        self._event_stream_max_s = 300.0
        # Sync-path single flight: key -> Future resolving to the leader's
        # (status, payload, content_type), or None when the leader errored.
        # Event-loop objects, so they live here rather than in the
        # thread-safe cache.
        self._sync_inflight: dict = {}
        # aiohttp's own cap is effectively disabled: _read_limited enforces
        # the per-route edge cap incrementally (bounded buffering), and an
        # explicit 0 (unlimited) must actually mean unlimited.
        self.app = web.Application(client_max_size=1024**4,
                                   middlewares=[self._auth_middleware])
        self.app.router.add_get("/v1/taskmanagement/task/{task_id}", self._task)
        self.app.router.add_get("/healthz", self._health)
        self.app.router.add_get("/metrics", self._metrics)
        self.app.on_cleanup.append(self._cleanup)

    def set_api_keys(self, keys: set[str] | None) -> None:
        """Enable (or clear) subscription-key auth on the public surface."""
        self._api_keys = set(keys) if keys else None

    def set_rate_limiter(self, limiter) -> None:
        """Enable (or clear with None) per-key request-rate throttling on
        the published surface — the APIM product-throttling slot
        (``gateway/ratelimit.py``). Applies to published APIs and task
        polling; NOT to the internal task-store surface riding this app
        (throttling workers' status updates would stall the data plane the
        limiter is protecting)."""
        self._rate_limiter = limiter

    def set_result_cache(self, cache) -> None:
        """Enable (or clear with None) the inference result cache +
        single-flight coalescing on published APIs (``rescache/``). Every
        cached route's response carries ``X-Cache: hit|miss|coalesced``
        (``bypass`` when the request opted out via ``X-Cache-Bypass`` or
        ``Cache-Control: no-cache``); uncached routes are unchanged."""
        self._result_cache = cache

    def set_admission(self, controller) -> None:
        """Enable (or clear with None) admission control on the published
        surface (``admission/``, ``docs/admission.md``): requests carry
        ``X-Deadline-Ms``/``X-Priority``; already-expired work answers 504
        with ``X-Shed-Reason`` instead of creating a task; the async edge
        sheds lowest-priority-first against the backlog; the sync proxy
        runs under the controller's adaptive in-flight cap; and every
        backpressure ``Retry-After`` is computed from the observed drain
        rate instead of a constant."""
        self._admission = controller

    def set_resilience(self, health) -> None:
        """Enable (or clear with None) resilient sync proxying
        (``resilience/``, ``docs/resilience.md``): weighted backend picks
        become health-aware (open-breaker backends ejected, their weight
        redistributed), a connection error retries against a *different*
        backend of the set under a retry budget with jittered backoff
        (instead of answering 502 after a single attempt), and backend
        response statuses feed the same breakers the dispatchers read."""
        self._resilience = health
        self._sync_retry_budget = (health.new_budget()
                                   if health is not None else None)

    def set_orchestration(self, orchestrator) -> None:
        """Enable (or clear with None) deadline/cost-aware placement on
        the sync proxy (``orchestration/``, ``docs/orchestration.md``):
        admitted POSTs are placed on the cheapest backend predicted to
        finish within their remaining budget (proxied RTTs feed the
        estimator), and the degradation ladder's brownout modes refuse
        classes beside the adaptive in-flight cap. Requires admission +
        resilience (the assembly enforces it)."""
        self._orchestration = orchestrator

    def set_observability(self, hub) -> None:
        """Enable (or clear with None) the request-observability layer
        (``observability/``, ``docs/observability.md``): every accepted
        async request gets ``admitted``/``published`` hop-ledger stamps,
        sheds and expiries feed the flight recorder, the sync proxy
        observes per-route end-to-end latency for the SLO engine, and
        ``GET /v1/debug/flight`` serves the tail-sampled flight-recorder
        dump. ``GET /v1/taskmanagement/task/{id}?ledger=1`` returns the
        task's timeline whenever the store carries one."""
        first = (self._observability is None and hub is not None
                 and not getattr(self, "_flight_route_added", False))
        self._observability = hub
        if hub is not None:
            # Backfill the backend→published route map for routes
            # registered before the hub was attached — async task
            # records carry the BACKEND endpoint, and the hub must
            # label their outcomes with the PUBLISHED prefix the SLO
            # objectives (and the refusal counters) use.
            for route in self.routes:
                if route.mode == "async":
                    hub.map_route(endpoint_path(route.backend_uri),
                                  route.prefix)
        if first:
            # Added lazily so a default gateway's route table stays
            # byte-identical; aiohttp accepts routes until the app runs.
            self._flight_route_added = True
            self.app.router.add_get("/v1/debug/flight", self._flight_dump)

    def set_event_stream(self, hub, max_stream_s: float = 300.0) -> None:
        """Enable (or clear with None) the streaming task-event surface
        (``pipeline/``, ``docs/pipelines.md``): ``GET /v1/taskmanagement/
        task/{id}/events`` serves the task's event stream — status
        transitions, pipeline stage partials, incremental chunks — as
        Server-Sent Events until the terminal event (or ``?wait=`` /
        ``max_stream_s`` expires). The route is added lazily so a
        pipeline-less gateway's route table stays byte-identical."""
        first = (self._event_hub is None and hub is not None
                 and not getattr(self, "_events_route_added", False))
        self._event_hub = hub
        self._event_stream_max_s = max_stream_s
        if first:
            self._events_route_added = True
            self.app.router.add_get(
                "/v1/taskmanagement/task/{task_id}/events",
                self._task_events)

    async def _task_events(self, request: web.Request) -> web.StreamResponse:
        """SSE stream of one task's events (docs/pipelines.md: ``status`` /
        ``stage`` / ``chunk`` / ``terminal``). Subscribe-then-re-read
        closes the attach race: the hub's subscribe replays buffered
        events under its lock, and any transition after the re-read below
        is published live — a terminal event can be delivered twice at
        the seam, never missed."""
        from ..pipeline.events import TERMINAL, sse_encode

        hub = self._event_hub
        if hub is None:
            return web.json_response(
                {"error": "event streaming not enabled"}, status=404)
        task_id = request.match_info["task_id"]
        try:
            task = await _aresult(self.store.get(task_id))
        except TaskNotFound:
            return web.Response(status=404, text="Task not found.")
        cap = self._event_stream_max_s
        try:
            wait = min(float(request.query.get("wait", cap)), cap)
        except ValueError:
            return web.Response(status=400, text="Bad wait parameter.")
        if not math.isfinite(wait):
            # nan/inf would defeat the stream-duration cap (min(nan, cap)
            # is nan, and the deadline arithmetic never expires).
            return web.Response(status=400, text="Bad wait parameter.")
        # SSE reconnect resume: the browser EventSource contract sends
        # the last consumed `id:` back as Last-Event-ID; replay restarts
        # strictly after it (?lastEventId= for manual clients). A resume
        # point inside chunk history the bounded replay already dropped
        # yields one synthetic `truncated` event (docs/streaming.md).
        raw_last = (request.headers.get("Last-Event-ID")
                    or request.query.get("lastEventId") or "0")
        try:
            after_seq = max(0, int(raw_last))
        except ValueError:
            return web.Response(status=400, text="Bad Last-Event-ID.")

        resp = web.StreamResponse(headers={
            "Content-Type": "text/event-stream",
            "Cache-Control": "no-cache",
            "X-Accel-Buffering": "no",
        })
        await resp.prepare(request)
        self._requests.inc(route="task_events", outcome="stream")
        stream = hub.subscribe(task_id, after_seq=after_seq)
        loop = asyncio.get_running_loop()
        deadline = loop.time() + wait
        try:
            # Current state first (the client may have attached late); the
            # re-read AFTER subscribing closes the attach-vs-event race.
            try:
                task = await _aresult(self.store.get(task_id))
            except TaskNotFound:
                task = None
            if task is not None:
                await resp.write(sse_encode(
                    {"seq": 0, "event": "status",
                     "data": {"Status": task.status,
                              "BackendStatus": task.backend_status}}))
                if task.canonical_status in TaskStatus.TERMINAL:
                    # Drain any buffered stage/chunk events before closing
                    # so a late subscriber still sees the run's shape
                    # (from its resume point; truncated marker included).
                    for event in hub.replay(task_id, after_seq=after_seq):
                        if event["event"] != TERMINAL:
                            await resp.write(sse_encode(event))
                    await resp.write(sse_encode(
                        {"seq": 0, "event": TERMINAL,
                         "data": task.to_dict()}))
                    return resp
            while True:
                timeout = min(15.0, deadline - loop.time())
                if timeout <= 0:
                    break
                try:
                    event = await stream.next_event(timeout=timeout)
                except asyncio.TimeoutError:
                    # Heartbeat comment keeps proxies from timing the
                    # stream out while a long stage runs.
                    await resp.write(b": keep-alive\n\n")
                    continue
                if event is None:
                    break
                await resp.write(sse_encode(event))
                if event["event"] == TERMINAL:
                    break
        except (ConnectionResetError, asyncio.CancelledError):
            raise  # client went away / server shutting down
        finally:
            await stream.aclose()
        return resp

    async def _flight_dump(self, _: web.Request) -> web.Response:
        hub = self._observability
        if hub is None or hub.flight is None:
            return web.json_response(
                {"error": "flight recorder not enabled"}, status=404)
        return web.json_response(hub.flight.dump())

    def set_quota_tracker(self, tracker) -> None:
        """Enable (or clear with None) per-key request QUOTAS — APIM's
        longer-horizon product cap beside the rate throttle. Same scope as
        the rate limiter; exhaustion answers 403 (APIM's quota status)
        with Retry-After = the window reset."""
        self._quota_tracker = tracker

    def set_tenancy(self, tenancy) -> None:
        """Enable (or clear with None) the multi-tenancy layer
        (``tenancy/``, ``docs/tenancy.md``): the subscription key resolves
        to a tenant once, HERE at the edge; work-creating requests on the
        published surface spend the tenant's token bucket (429 with a
        drain-derived ``Retry-After`` on refusal — composed with, never
        replacing, the per-key throttle above and the admission shedder
        below); and the resolved tenant id rides the task record so the
        broker lanes, the dispatcher's cost charge, and the per-tenant
        series all scope by it. Off (None) → nothing resolved, nothing
        stamped: the pre-tenancy path byte for byte."""
        self._tenancy = tenancy

    @web.middleware
    async def _auth_middleware(self, request: web.Request, handler):
        """Subscription-key gate — the APIM front-door behavior (every
        reference API call carries ``Ocp-Apim-Subscription-Key``). When keys
        are set, EVERYTHING on this app except health/metrics requires one —
        including the task-store surface when it rides this port (an open
        ``/v1/taskstore/*`` beside a keyed public API would hand out the
        same task data the 401 just protected); workers attach the key via
        ``AI4E_SERVICE_TASKSTORE_API_KEY``.
        """
        exempt = (request.path in ("/healthz", "/metrics"))
        key = (request.headers.get("Ocp-Apim-Subscription-Key")
               or request.headers.get("X-Api-Key"))
        if self._api_keys is not None and not exempt:
            if key not in self._api_keys:
                # Constant label: the path is attacker-chosen and would
                # grow metric cardinality without bound.
                self._requests.inc(route="unauthorized", outcome="401")
                return web.json_response(
                    {"error": "missing or invalid subscription key"},
                    status=401)
        throttled = ((self._rate_limiter is not None
                      or self._quota_tracker is not None)
                     and not exempt
                     and not request.path.startswith("/v1/taskstore/"))
        if throttled:
            # Bucket by the subscription key ONLY when auth validated it
            # (above) — with auth off the header is attacker-chosen and
            # rotating it would mint a fresh bucket per request; bucket by
            # caller address instead.
            identity = (key if self._api_keys is not None
                        else (request.remote or "anonymous"))
            # Quota PEEK first (non-consuming): an exhausted key gets the
            # 403 with its window-reset Retry-After without burning rate
            # tokens it would need once the window rolls.
            if self._quota_tracker is not None:
                allowed, retry_after = self._quota_tracker.would_allow(
                    identity)
                if not allowed:
                    self._requests.inc(route="throttled", outcome="403")
                    return web.json_response(
                        {"error": "quota exceeded"}, status=403,
                        headers={"Retry-After":
                                 str(max(1, math.ceil(retry_after)))})
            if self._rate_limiter is not None:
                allowed, retry_after = self._rate_limiter.allow(identity)
                if not allowed:
                    # A rate-refused request has consumed no quota (the
                    # peek above doesn't count).
                    self._requests.inc(route="throttled", outcome="429")
                    return web.json_response(
                        {"error": "rate limit exceeded"}, status=429,
                        # RFC 7231 delta-seconds: integer, minimum 1.
                        headers={"Retry-After":
                                 str(max(1, math.ceil(retry_after)))})
            if self._quota_tracker is not None:
                self._quota_tracker.allow(identity)  # consume the unit
        if (self._tenancy is not None and not exempt
                and not request.path.startswith("/v1/taskstore/")):
            # Tenant scope resolves ONCE, here at the edge — downstream
            # hops read the resolved id, never the key. The tenant bucket
            # is spent only by WORK-CREATING requests (published routes):
            # status polls and event streams cost the platform nothing a
            # quota contract meters, and charging them would let a slow
            # backend double-bill its own tenant's polling.
            tenant = self._tenancy.resolve(key)
            request["ai4e_tenant"] = tenant.tenant_id
            if self._published_route(request.path):
                allowed, retry_after = self._tenancy.admit(tenant.tenant_id)
                if not allowed:
                    if self._admission is not None:
                        # Compose with the admission drain estimate: back
                        # off for whichever bottleneck is slower — the
                        # tenant's own refill or the platform's drain.
                        retry_after = max(retry_after,
                                          self._admission.retry_after_s())
                    self._tenancy.note_quota_shed(tenant.tenant_id)
                    self._requests.inc(route="throttled",
                                       outcome="tenant_429")
                    return web.json_response(
                        {"error": "tenant quota exceeded"}, status=429,
                        headers={"Retry-After":
                                 str(max(1, math.ceil(retry_after))),
                                 SHED_REASON_HEADER:
                                 shed_reason("gateway", "tenant-quota")})
                self._tenancy.note_admitted(tenant.tenant_id)
        return await handler(request)

    def _published_route(self, path: str) -> bool:
        """Whether a request path targets a published API (the
        work-creating surface the tenant bucket meters)."""
        for route in self.routes:
            if path == route.prefix or path.startswith(route.prefix + "/"):
                return True
        return False

    def add_async_route(self, prefix: str, task_endpoint,
                        max_body_bytes: int | None = None) -> None:
        """Register an async API: requests become tasks addressed to
        ``task_endpoint`` (the backend route the dispatcher will POST to —
        a URI, or a weighted backend set whose primary becomes the recorded
        endpoint). ``max_body_bytes``: per-route edge cap (None → the
        gateway's). Cacheability is derived HERE, same as the sync route —
        a weighted canary set must not share one cache entry across
        backends serving different model versions, and a caller must not be
        able to forget that."""
        backends = normalize_backends(task_endpoint)
        route = Route(prefix=prefix.rstrip("/"), mode="async",
                      backend_uri=backends[0][0],
                      max_body_bytes=max_body_bytes,
                      cacheable=len(backends) == 1)
        self.routes.append(route)
        if self._observability is not None:
            # One route label for the whole request shape — see
            # set_observability's backfill.
            self._observability.map_route(
                endpoint_path(route.backend_uri), route.prefix)
        self.app.router.add_post(route.prefix, self._make_async_handler(route))
        self.app.router.add_post(route.prefix + "/{tail:.*}",
                                 self._make_async_handler(route))

    def add_sync_route(self, prefix: str, backend_uri,
                       max_body_bytes: int | None = None) -> None:
        backends = [(u.rstrip("/"), w)
                    for u, w in normalize_backends(backend_uri)]
        route = Route(prefix=prefix.rstrip("/"), mode="sync",
                      backend_uri=backends[0][0],
                      backends=backends,
                      max_body_bytes=max_body_bytes,
                      # A weighted canary set must not share one cache entry
                      # across backends serving different model versions.
                      cacheable=len(backends) == 1)
        self.routes.append(route)
        handler = self._make_sync_handler(route)
        for pattern in (route.prefix, route.prefix + "/{tail:.*}"):
            self.app.router.add_route("*", pattern, handler)

    # -- async: edge task creation (request_policy.xml:8-28) ---------------

    def _route_limit(self, route: Route) -> int:
        """The route's effective edge cap, resolved at request time so a
        gateway-level cap set after routes were registered still applies."""
        return (self.max_body_bytes if route.max_body_bytes is None
                else route.max_body_bytes)

    async def _read_limited(self, request: web.Request,
                            route: Route) -> bytes | None:
        """Body within the route's edge cap, else None (→ 413)."""
        from ..utils.http import read_body_limited
        return await read_body_limited(request, self._route_limit(route))

    def _payload_too_large(self, route: Route) -> web.Response:
        self._requests.inc(route=route.prefix, outcome="413")
        return web.Response(
            status=413,
            text=f"Payload exceeds {self._route_limit(route)} bytes.")

    def _make_async_handler(self, route: Route):
        async def handler(request: web.Request) -> web.Response:
            # Hop-ledger anchor (observability/): the ``admitted`` event
            # carries the request's ARRIVAL time, appended once the
            # record exists — so gateway processing time is visible as
            # the admitted→published delta.
            arrival = time.time() if self._observability is not None else 0.0
            body = await self._read_limited(request, route)
            if body is None:
                return self._payload_too_large(route)
            # Record the full target: base backend URI + operation tail +
            # query, so the dispatcher can reproduce the exact call (the
            # reference stores the original request URI as Endpoint,
            # request_policy.xml:15).
            endpoint = route.backend_uri
            tail = request.match_info.get("tail", "")
            if tail:
                endpoint = endpoint.rstrip("/") + "/" + tail
            if request.query_string:
                endpoint += "?" + request.query_string
            from ..taskstore import JournalDegradedError, NotPrimaryError
            content_type = request.content_type or "application/json"

            # Admission (admission/): anchor the caller's relative budget
            # to an absolute deadline, classify, and 504 already-dead work
            # HERE — before any task state exists. The PRESSURE shed runs
            # later, after the cache consult: a request servable from the
            # cache (or coalescible onto an in-flight leader) adds no
            # backlog, so refusing it under backlog pressure would cost a
            # free answer. Off (None) → nothing parsed, nothing stamped:
            # the pre-admission path byte for byte.
            deadline_at = 0.0
            task_priority = 1
            if self._admission is not None:
                deadline_at = parse_deadline_at(request.headers)
                task_priority = parse_priority(request.headers)
                refusal = self._admission_expired(route, task_priority,
                                                  deadline_at)
                if refusal is not None:
                    return refusal

            # Result-cache consult (rescache/): hit → terminal task served
            # straight from the cache; identical request already in flight →
            # hand back the SAME task record (single-flight coalescing, no
            # second execution); miss → stamp the key on the task so the
            # store listener fills the cache on completion.
            cache = self._result_cache if route.cacheable else None
            cache_key = ""
            xcache = None
            if cache is not None:
                if cache_bypass_requested(request.headers):
                    xcache = "bypass"
                else:
                    key = self._derive_cache_key(route, request, body,
                                                 content_type)
                    with self.tracer.span("cache_lookup", route=route.prefix,
                                           headers=request.headers) as span:
                        # count=False: the outcome is counted exactly once
                        # below, when it is KNOWN — a lookup that ends up
                        # coalescing must not also record a miss, or the
                        # hit ratio understates the cache under duplicate
                        # load (docs/METRICS.md: outcomes sum to requests).
                        found = cache.get(key, count=False)
                        leader = None if found else cache.leader_for(key)
                        span.attrs["outcome"] = ("hit" if found
                                                 else "coalesced" if leader
                                                 else "miss")
                    if found is not None:
                        resp = await self._serve_cached_task(
                            route, endpoint, body, content_type, key, found)
                        if resp is not None:
                            cache.count_hit()
                            return resp
                        # Standby replica (cannot create the record): fall
                        # through UNCOUNTED — the create path answers
                        # not-primary below, and a request that neither
                        # executed nor was served has no cache outcome
                        # (docs/METRICS.md: outcomes sum to requests).
                    else:
                        if leader is not None:
                            try:
                                record = await _aresult(
                                    self.store.get(leader))
                            except TaskNotFound:
                                # Leader evicted mid-flight (tight
                                # retention): clear the stale registration,
                                # execute fresh.
                                cache.release_inflight(key, leader)
                            else:
                                cache.count_coalesced()
                                self._requests.inc(route=route.prefix,
                                                   outcome="coalesced")
                                return web.json_response(
                                    record.to_dict(),
                                    headers={CACHE_STATUS_HEADER: "coalesced"})
                        cache_key = key
                        xcache = "miss"
            if self._admission is not None:
                # Pressure shed, now that the cache had its chance: only
                # requests about to CREATE work are tested against the
                # route's backlog. Nothing to unwind on refusal — the
                # miss/bypass outcome is counted after record creation and
                # inflight leadership is registered after it too, so a
                # shed here leaves no cache state behind.
                refusal = self._admission_pressure(route, task_priority,
                                                   deadline_at)
                if refusal is not None:
                    return refusal
            with self.tracer.span("create_task", route=route.prefix,
                                   headers=request.headers) as span:
                try:
                    task = await _aresult(self.store.upsert(APITask(
                        endpoint=endpoint,
                        body=body,
                        content_type=content_type,
                        publish=True,
                        cache_key=cache_key,
                        deadline_at=deadline_at,
                        priority=task_priority,
                        tenant=request.get("ai4e_tenant", ""),
                    )))
                except NotPrimaryError:
                    # Standby control plane: reads are served here, task
                    # creation belongs to the primary — tell the client to
                    # retry (the LB/DNS flips after failover promotion).
                    self._requests.inc(route=route.prefix,
                                       outcome="not_primary")
                    return web.json_response(
                        {"error": "standby replica; task creation is on "
                                  "the primary"},
                        status=503,
                        # Same marker as the store surface: clients with a
                        # replica list rotate ONLY on this header — a plain
                        # overload 503 must never re-home them (ADVICE r4).
                        # Retry-After is drain-rate-derived when admission
                        # runs (satellite: no hardcoded backoff hints).
                        headers={"Retry-After": self._standby_retry_after(),
                                 "X-Not-Primary": "1"})
                except JournalDegradedError as exc:
                    # Journal disk fault (docs/durability.md): the store
                    # is fenced read-only — nothing was created or
                    # published (memory never runs ahead of disk), so
                    # refuse with the typed 503 the resilience layer
                    # treats like a dark backend. No X-Not-Primary:
                    # reads still serve here; clients must not re-home.
                    self._requests.inc(route=route.prefix,
                                       outcome="journal_degraded")
                    if self._observability is not None:
                        # The flight recorder keeps 100% of refusals —
                        # a degraded store mid-incident ships its own
                        # evidence (observability/hub.py).
                        self._observability.record_refusal(
                            route.prefix, "journal-degraded",
                            priority=task_priority)
                    return web.json_response(
                        {"error": f"journal degraded: {exc}"},
                        status=503,
                        headers={"Retry-After": self._standby_retry_after(),
                                 SHED_REASON_HEADER: "journal-degraded"})
                span.task_id = task.task_id
            if cache is not None and xcache is not None:
                # Miss/bypass recorded only NOW, after the record exists: a
                # standby's NotPrimaryError 503 above must not count an
                # outcome once per client retry (docs/METRICS.md: outcomes
                # sum to answered requests). Hit/coalesced returned earlier.
                (cache.count_miss if xcache == "miss"
                 else cache.count_bypass)()
            stored = await _aresult(self.store.get(task.task_id))
            if self._observability is not None:
                # admitted (at arrival time) + published: the store's
                # publish hook ran synchronously inside upsert, so by
                # here the task is on the transport.
                self._observability.stamp(
                    task.task_id,
                    ledger_event(ADMITTED, "gateway", t=arrival,
                                 reason=route.prefix),
                    ledger_event(PUBLISHED, "gateway"))
            if cache_key and stored.canonical_status not in TaskStatus.TERMINAL:
                # This task is now the one execution owning the key; the
                # store listener releases it on the terminal transition
                # (rescache/wiring.py). A task that is ALREADY terminal here
                # (synchronous publish failure) registers nothing.
                cache.register_inflight(cache_key, task.task_id)
            outcome = "failed" if stored.canonical_status == "failed" else "created"
            self._requests.inc(route=route.prefix, outcome=outcome)
            return web.json_response(
                stored.to_dict(),
                headers={CACHE_STATUS_HEADER: xcache} if xcache else None)

        return handler

    def _admission_expired(self, route: Route, priority: int,
                           deadline_at: float) -> web.Response | None:
        """504 for async work whose budget is already spent — creating a
        task would only carry a corpse through the broker. Runs BEFORE the
        cache consult: even a cached answer serves nobody here."""
        if not expired(deadline_at):
            return None
        self._admission.note_expired("gateway", priority)
        self._requests.inc(route=route.prefix, outcome="expired")
        if self._observability is not None:
            self._observability.record_refusal(route.prefix, "expired",
                                               priority=priority)
        return web.Response(
            status=504, text="Deadline already expired.",
            headers={SHED_REASON_HEADER: shed_reason("gateway", "deadline")})

    def _admission_pressure(self, route: Route, priority: int,
                            deadline_at: float) -> web.Response | None:
        """429 lowest-priority-first when the route's created backlog says
        new work would queue past its class's share (or past its own
        deadline) — with a ``Retry-After`` computed from the observed
        drain rate and ``X-Shed-Reason`` provenance. Runs AFTER the cache
        consult: only requests about to create backlog are tested."""
        adm = self._admission
        try:
            backlog = self.store.set_len(endpoint_path(route.backend_uri),
                                         TaskStatus.CREATED)
        except Exception:  # noqa: BLE001; ai4e: noqa[AIL005] — duck-typed store stand-ins in tests lack set_len; empty backlog is the correct degraded answer
            backlog = 0
        decision = adm.shed_async(priority, backlog, deadline_at)
        if decision is None:
            return None
        retry_after, why = decision
        adm.note_shed("gateway", priority)
        self._requests.inc(route=route.prefix, outcome="shed")
        if self._observability is not None:
            self._observability.record_refusal(route.prefix, why,
                                               priority=priority)
        return web.json_response(
            {"error": f"request shed ({why}); retry later"},
            status=429,
            headers={"Retry-After": str(max(1, math.ceil(retry_after))),
                     SHED_REASON_HEADER: shed_reason("gateway", why)})

    def _standby_retry_after(self) -> str:
        """Retry-After on the standby-replica 503. With admission running
        this is the drain-rate estimate (how long until the backlog the
        promotion inherits should clear a unit of work); without it, the
        historical constant."""
        if self._admission is None:
            return "2"
        return str(max(1, math.ceil(self._admission.retry_after_s())))

    def _derive_cache_key(self, route: Route, request: web.Request,
                          body: bytes, content_type: str) -> str:
        """Canonical result-cache key for a gateway request — the ONE
        derivation both the async and the sync handler use, so the two
        paths can never drift into separate key namespaces for the same
        request (keys must also match what the dispatcher re-derives on
        redelivery)."""
        tail = request.match_info.get("tail", "")
        return request_key(
            endpoint_path(route.backend_uri), body, content_type,
            extra=(tail + "?" + request.query_string
                   if request.query_string else tail))

    async def _serve_cached_task(self, route: Route, endpoint: str,
                                 body: bytes, content_type: str, key: str,
                                 found: tuple) -> web.Response | None:
        """Answer an async-path cache hit. A REAL task record is created —
        already terminal, ``publish=False`` so it never touches the
        transport — and the cached payload is stored as its result, so the
        client contract (poll the TaskId, fetch ``/v1/taskstore/result``)
        holds identically for hits and misses. ``durable=False``: this
        response already carries the terminal record, so the record is
        memory-only — a journaled store must not pay payload-sized journal
        appends per duplicate request (the workload the cache exists for);
        after a restart the TaskId 404s, same as zero-retention reaping.
        Returns None when this replica cannot create records (standby or
        journal-degraded) — the caller falls through to the ordinary
        create path, whose typed handlers answer not-primary and
        journal-degraded 503s (a degraded store refuses even this
        memory-only record: the cache hit must not leak a generic 500
        where every other mutation ships X-Shed-Reason)."""
        from ..taskstore import JournalDegradedError, NotPrimaryError
        payload, ctype = found
        try:
            task = await _aresult(self.store.upsert(APITask(
                endpoint=endpoint, body=body, content_type=content_type,
                status="completed - served from cache",
                backend_status=TaskStatus.COMPLETED,
                publish=False, cache_key=key, durable=False)))
        except (NotPrimaryError, JournalDegradedError):
            return None
        try:
            await _aresult(self.store.set_result(task.task_id, payload,
                                                 ctype))
        except TaskNotFound:
            pass  # reaped already (zero-retention config); record answered
        except JournalDegradedError:
            # Degraded raced in between: the memory-only record exists
            # but its result cannot attach — fall through to the create
            # path's typed 503 (the orphan is non-durable and reaped).
            return None
        self._requests.inc(route=route.prefix, outcome="cache_hit")
        return web.json_response(task.to_dict(),
                                 headers={CACHE_STATUS_HEADER: "hit"})

    # -- sync: reverse proxy (request_backend_policy.xml:1-6) --------------

    def _make_sync_handler(self, route: Route):
        async def handler(request: web.Request) -> web.Response:
            tail = request.match_info.get("tail", "")
            body = await self._read_limited(request, route)
            if body is None:
                return self._payload_too_large(route)

            # Result cache on the sync proxy: POST-only (inference requests;
            # GETs and friends pass through untouched). A hit answers from
            # the cache; an identical request already proxying makes this
            # one a single-flight subscriber — it awaits the leader's
            # response instead of re-executing.
            # Admission on the sync proxy (admission/): POST-only, like the
            # cache — POSTs are the inference requests; GETs and friends
            # pass through untouched. An already-expired request answers
            # 504 before the cache or the backend see it; admitted ones
            # run under the controller's adaptive in-flight cap (acquired
            # below, inside the try/finally).
            adm = self._admission if request.method == "POST" else None
            sync_scope = None
            priority = 1
            deadline_at = 0.0
            if adm is not None:
                deadline_at = parse_deadline_at(request.headers)
                priority = parse_priority(request.headers)
                if expired(deadline_at):
                    adm.note_expired("gateway_sync", priority)
                    self._requests.inc(route=route.prefix, outcome="expired")
                    if self._observability is not None:
                        self._observability.record_refusal(
                            route.prefix, "expired", priority=priority)
                    return web.Response(
                        status=504, text="Deadline already expired.",
                        headers={SHED_REASON_HEADER:
                                 shed_reason("gateway_sync", "deadline")})
                sync_scope = adm.scope(adm.SYNC_SCOPE)

            cache = self._result_cache if route.cacheable else None
            key = None
            fut = None  # set when THIS request is the single-flight leader
            gen = 0  # family invalidation generation captured at leadership
            bypassed = False
            # Outcome counting is DEFERRED until the request survives the
            # admission acquire below: a miss/bypass recorded here and
            # then shed with 503 would count an outcome for a request
            # that never executed (docs/METRICS.md: outcomes sum to
            # executing/served requests — the same reason the async path
            # counts only after the task record exists).
            miss_pending = False
            if cache is not None and request.method == "POST":
                if cache_bypass_requested(request.headers):
                    bypassed = True
                else:
                    key = self._derive_cache_key(route, request, body,
                                                 request.content_type or "")
                    # count=False + explicit outcome below: one external
                    # request, exactly one of hit/miss/coalesced.
                    found = cache.get(key, count=False)
                    if found is not None:
                        cache.count_hit()
                        self._requests.inc(route=route.prefix,
                                           outcome="cache_hit")
                        return web.Response(
                            body=found[0], content_type=found[1],
                            headers={CACHE_STATUS_HEADER: "hit"})
                    waiting = self._sync_inflight.get(key)
                    if waiting is not None:
                        leader_fut, leader_gen = waiting
                        settled = await leader_fut
                        if (settled is not None
                                and cache.generation(key) == leader_gen):
                            status, payload, ctype = settled
                            cache.count_coalesced()
                            self._requests.inc(route=route.prefix,
                                               outcome="coalesced")
                            return web.Response(
                                status=status, body=payload,
                                content_type=ctype,
                                headers={CACHE_STATUS_HEADER: "coalesced"})
                        # Leader errored out, OR a checkpoint reload
                        # invalidated the family after the leader captured
                        # its generation — its execution used the OLD
                        # weights and must not be adopted (the same
                        # generation check that already guards the cache
                        # fill, applied to coalescing). Proxy ourselves,
                        # uncoalesced (no re-registration: an erroring
                        # backend must not chain a convoy of waiters behind
                        # each retry). If this request executes (survives
                        # admission), it is a miss.
                        miss_pending = True
                        key = None
                    else:
                        fut = asyncio.get_running_loop().create_future()
                        gen = cache.generation(key)
                        self._sync_inflight[key] = (fut, gen)
                        miss_pending = True

            # From the moment the leader future is registered, EVERY exit —
            # backend errors, unexpected exceptions, the client
            # disconnecting (aiohttp cancels the handler wherever it is
            # suspended) — must run the finally below, or the unresolved
            # future wedges every later identical request forever.
            import time as _time
            acquired = False
            t0 = _time.perf_counter()
            try:
                if sync_scope is not None:
                    # Brownout check FIRST (orchestration ladder): a
                    # declared degraded mode refuses the class before any
                    # occupancy math — cache hits already answered above,
                    # which is exactly the ladder's cache-only contract.
                    # Inside the try for the same reason as the shed
                    # below: a refused leader's finally must resolve the
                    # single-flight future.
                    brown = adm.brownout_refusal(priority)
                    if brown is not None:
                        brown_after, _mode = brown
                        adm.note_shed("gateway_sync", priority)
                        self._requests.inc(route=route.prefix,
                                           outcome="shed")
                        if self._observability is not None:
                            self._observability.record_refusal(
                                route.prefix, "brownout",
                                priority=priority)
                        return web.Response(
                            status=503, text="Service degraded (brownout).",
                            headers={"Retry-After":
                                     str(max(1, math.ceil(brown_after))),
                                     SHED_REASON_HEADER:
                                     shed_reason("gateway_sync",
                                                 "brownout")})
                    # Adaptive in-flight cap, lowest priority shed first.
                    # Inside the try: a shed leader's finally still
                    # resolves the single-flight future (waiters then
                    # proxy themselves and face their own admission).
                    retry_after = sync_scope.try_acquire(priority)
                    if retry_after is not None:
                        adm.note_shed("gateway_sync", priority)
                        self._requests.inc(route=route.prefix,
                                           outcome="shed")
                        if self._observability is not None:
                            self._observability.record_refusal(
                                route.prefix, "pressure",
                                priority=priority)
                        return web.Response(
                            status=503, text="Sync capacity exhausted.",
                            headers={"Retry-After":
                                     str(max(1, math.ceil(retry_after))),
                                     SHED_REASON_HEADER:
                                     shed_reason("gateway_sync",
                                                 "pressure")})
                    acquired = True
                # Admitted: the request WILL execute — now the deferred
                # cache outcome is true.
                if cache is not None:
                    if miss_pending:
                        cache.count_miss()
                    elif bypassed:
                        cache.count_bypass()
                # Strip hop headers AND the gateway credential: a sync
                # backend (arbitrary URI, possibly third-party) must
                # never see the subscription key it could replay
                # against the keyed public surface. With admission,
                # the RELATIVE deadline header is stripped too and
                # the ABSOLUTE one attached — re-anchoring
                # X-Deadline-Ms at the worker would extend the
                # budget by exactly the proxy time it bounds.
                fwd_headers = {
                    **{k: v for k, v in request.headers.items()
                       if k.lower() not in (
                           "host", "content-length",
                           "ocp-apim-subscription-key", "x-api-key",
                           *(("x-deadline-ms", "x-deadline-at",
                              "x-priority")
                             if sync_scope is not None else ()))},
                    **(propagation_headers(deadline_at, priority)
                       if sync_scope is not None else {})}
                res = self._resilience
                tried: list[str] = []
                attempt = 0
                if self._sync_retry_budget is not None:
                    self._sync_retry_budget.on_request()
                while True:
                    attempt += 1
                    # Weighted per-request pick over the route's backend set
                    # (single-backend routes skip the RNG) — Istio's
                    # weighted VirtualService subsets, at the gateway;
                    # health-aware under resilience (open backends ejected);
                    # deadline/cost-aware for admitted POSTs under
                    # orchestration (cheapest backend predicted to finish
                    # within the remaining budget).
                    if (sync_scope is not None
                            and self._orchestration is not None):
                        base = self._orchestration.place(
                            route.backends, deadline_at=deadline_at,
                            priority=priority, exclude=tried)
                    elif res is not None:
                        base = res.pick(route.backends, exclude=tried)
                    else:
                        base = pick_backend(route.backends)
                    target = base + (("/" + tail) if tail else "")
                    if request.query_string:
                        target += "?" + request.query_string
                    session = await self._get_session()
                    attempt_t0 = _time.perf_counter()
                    orch = (self._orchestration if sync_scope is not None
                            else None)
                    if orch is not None:
                        # Queue-pressure input for the completion
                        # estimator — the same begin/finally-end pairing
                        # the dispatcher wraps its POST in, so sync
                        # in-flight load discounts p_within too instead
                        # of the proxy overloading a tier the estimator
                        # still thinks is idle.
                        orch.begin(base)
                    try:
                        async with session.request(
                            request.method, target, data=body,
                            headers=fwd_headers,
                        ) as resp:
                            payload = await resp.read()
                            if (orch is not None
                                    and 200 <= resp.status < 300):
                                # Proxied completion RTT feeds the
                                # estimator — on the sync path this IS
                                # the end-to-end service time. Gated on
                                # the SAME condition as placement
                                # (admitted POSTs): a route's GET
                                # health/status probes answer in
                                # microseconds and would teach the
                                # sketch a service time no inference
                                # POST will ever see.
                                self._orchestration.observe(
                                    base,
                                    _time.perf_counter() - attempt_t0)
                            if res is not None:
                                # Breakers read the proxied status too —
                                # 5xx (not 503 backpressure) is failure
                                # evidence; the RESPONSE still goes to the
                                # client untouched (the backend executed;
                                # replaying a non-idempotent inference POST
                                # that answered is not the proxy's call).
                                res.observe_status(base, resp.status)
                                if resp.headers.get("X-Draining"):
                                    # Rollout drain marker: eject this
                                    # backend from the proxy's picks for
                                    # a TTL — it told us it is leaving
                                    # (docs/deployment.md#drain).
                                    res.mark_draining(base)
                            self._requests.inc(route=route.prefix,
                                               outcome=str(resp.status))
                            if (self._observability is not None
                                    and request.method == "POST"):
                                # Per-route e2e latency + outcome for
                                # the SLO engine (POST-only — the same
                                # inference-request gate admission and
                                # the cache use).
                                self._observability.observe_sync(
                                    route.prefix,
                                    _time.perf_counter() - t0,
                                    resp.status)
                            if fut is not None:
                                # Only successes become cache entries — and
                                # only when the family's invalidation
                                # generation still matches the one captured
                                # at leadership (a checkpoint reload
                                # mid-proxy means this result came from the
                                # OLD weights; refuse the stale fill). The
                                # waiters get whatever the backend said
                                # regardless (it IS their request's
                                # response — errors included).
                                if resp.status == 200:
                                    cache.put(key, payload,
                                              resp.content_type,
                                              if_generation=gen)
                                fut.set_result((resp.status, payload,
                                                resp.content_type))
                            return web.Response(
                                status=resp.status, body=payload,
                                content_type=resp.content_type,
                                # Same X-Cache contract as the async path
                                # (docs/API.md): leader → miss, opted out →
                                # bypass; a waiter-turned-executor (leader
                                # errored) carries no header — it neither
                                # led nor consulted the cache for its
                                # answer.
                                headers=({CACHE_STATUS_HEADER: "miss"}
                                         if fut is not None
                                         else {CACHE_STATUS_HEADER: "bypass"}
                                         if bypassed else None))
                    except (aiohttp.ClientError,
                            asyncio.TimeoutError) as exc:
                        # Under resilience every transport failure is
                        # breaker evidence (and resolves a probe slot),
                        # but only a CONNECT-phase failure may retry: the
                        # request never reached the backend, so replaying
                        # it is safe for any method. A timeout or a
                        # mid-response disconnect may have EXECUTED a
                        # non-idempotent inference POST — unlike the async
                        # path there is no duplicate suppression here, so
                        # those answer 502 without failover (same rule as
                        # refusing to replay an answered 5xx). Resilience
                        # off keeps today's behavior exactly: single
                        # attempt, ClientError → 502, timeout propagates.
                        if res is not None:
                            res.record_failure(base)
                            if (isinstance(exc, aiohttp.ClientConnectorError)
                                    and attempt < res.policy.max_attempts
                                    and self._sync_retry_budget.try_retry()):
                                from ..resilience.retry import backoff_s
                                tried.append(base)
                                res.note_failover("gateway_sync")
                                await asyncio.sleep(backoff_s(
                                    attempt, res.policy.retry_base_s,
                                    res.policy.retry_cap_s))
                                continue
                        elif isinstance(exc, asyncio.TimeoutError):
                            raise
                        self._requests.inc(route=route.prefix,
                                           outcome="unreachable")
                        if (self._observability is not None
                                and request.method == "POST"):
                            self._observability.observe_sync(
                                route.prefix,
                                _time.perf_counter() - t0, 502)
                        return web.Response(
                            status=502,
                            text=f"Backend unreachable: {exc}")
                    finally:
                        if orch is not None:
                            orch.end(base)
            finally:
                if acquired:
                    # Observe BEFORE release, so the limiter's Little's-law
                    # clamp sees the in-flight count including this request
                    # (the dispatcher path passes its _busy counter the
                    # same way) — observing after the decrement would
                    # record inflight=0 under serial traffic and let the
                    # limit ratchet to the ceiling unused. RTT feeds the
                    # limiter ONLY for requests that held a slot — shed
                    # paths return in microseconds and would teach it a
                    # fictitious no-load RTT.
                    sync_scope.observe(_time.perf_counter() - t0)
                    sync_scope.release()
                if fut is not None:
                    self._sync_inflight.pop(key, None)
                    if not fut.done():
                        fut.set_result(None)  # waiters proxy themselves

        return handler

    # -- task polling (task_management_policy.xml:3-7) ---------------------

    MAX_LONG_POLL = 60.0

    async def _task(self, request: web.Request) -> web.Response:
        """Task status; ``?wait=SECONDS`` long-polls until the task reaches a
        terminal state (or the wait expires) instead of making the client
        spin on 5 ms GETs — the reference's polling contract
        (``GET /task/{taskId}``) with the poll storm removed. Event-driven:
        the store's change listener wakes exactly the waiters for that task.
        """
        task_id = request.match_info["task_id"]

        async def answer(record) -> web.Response:
            """The poll response; ``?ledger=1`` (opt-in — the default
            wire shape is byte-identical) attaches the task's hop-ledger
            timeline when the store carries one
            (docs/observability.md). Await-transparent like every store
            verb: the rig's ring store fetches the timeline from the
            OWNING shard node over the wire."""
            payload = record.to_dict()
            if request.query.get("ledger", "") not in ("", "0", "false"):
                getter = getattr(self.store, "get_ledger", None)
                payload["Ledger"] = (await _aresult(getter(task_id))
                                     if getter else [])
            return web.json_response(payload)

        try:
            task = await _aresult(self.store.get(task_id))
        except TaskNotFound:
            return web.Response(status=404, text="Task not found.")

        wait = 0.0
        if "wait" in request.query:
            try:
                wait = min(float(request.query["wait"]), self.MAX_LONG_POLL)
            except ValueError:
                return web.Response(status=400, text="Bad wait parameter.")

        if wait > 0 and task.canonical_status not in TaskStatus.TERMINAL:
            # Park on the task's change feed (``taskstore/feed.py``) — the
            # ONE wake mechanism for every store shape. The wakeup delivers
            # the terminal record itself — no per-request store re-poll —
            # and the feed's replay map closes the attach-vs-event race, so
            # the whole watcher population rides N feeds instead of
            # N×watchers store listeners. Only the timeout path (a task
            # that migrated shards mid-wait, an evicted task, a wire feed
            # that hiccuped) falls back to a store read — which is also
            # where a mid-wait eviction answers 404, not 500.
            record = await self._feed_for(task_id).wait_terminal(task_id,
                                                                 wait)
            if record is not None:
                return await answer(record)
            try:
                task = await _aresult(self.store.get(task_id))
            except TaskNotFound:
                return web.Response(status=404, text="Task not found.")
        return await answer(task)

    def _feed_for(self, task_id: str):
        """The change feed a long-poll for ``task_id`` parks on: the
        store's own feed when it has one (the sharded facade's owning
        shard, the rig wire store's locally-tailed shard feed), else one
        gateway-side feed lazily attached to the store's listener surface.
        This replaced the per-task waiter map that lived beside the feed
        path: the feed wakes with the record, behaves identically when
        the transition arrives via a replication absorb, and is the same
        mechanism another gateway replica uses — so a long-poll answered
        by a replica that did not admit the task still wakes with the
        record (tests/test_longpoll.py)."""
        feed_for = getattr(self.store, "feed_for", None)
        if feed_for is not None:
            return feed_for(task_id)
        if self._fallback_feed is None:
            from ..taskstore.feed import ShardChangeFeed
            feed = ShardChangeFeed(0)
            add = getattr(self.store, "add_listener", None)
            if add is not None:
                add(feed.publish)
            self._fallback_feed = feed
        return self._fallback_feed

    async def _health(self, _: web.Request) -> web.Response:
        return web.json_response({"status": "healthy", "routes": len(self.routes)})

    async def _metrics(self, _: web.Request) -> web.Response:
        return web.Response(text=self.metrics.render_prometheus(),
                            content_type="text/plain")

    async def _get_session(self) -> aiohttp.ClientSession:
        return await self._sessions.get()

    async def _cleanup(self, _app) -> None:
        await self._sessions.close()

    def run(self, host: str = "0.0.0.0", port: int = 8080) -> None:
        web.run_app(self.app, host=host, port=port)
