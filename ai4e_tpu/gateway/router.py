"""Gateway — the platform's front door.

Re-design of the reference's Azure API Management layer (L1). The APIM inbound
policy for an async API builds a task record at the edge and returns the
TaskId synchronously while the transport delivers the work
(``APIManagement/request_policy.xml:3-36``); sync APIs pass straight through to
the cluster ingress (``request_backend_policy.xml:1-16``); task polling hits
the store (``task_management_policy.xml:1-18``). Here those three policies are
one aiohttp app with a programmatic route table instead of az-CLI-deployed XML
(``APIManagement/create_async_api_management_api.sh:52-80``).

Routes:
- ``POST {route.prefix}/…``  (async) → upsert task {Status: created, Endpoint,
  Body, publish: True} → broker; respond 200 with the task JSON immediately;
- ``ANY  {route.prefix}/…``  (sync)  → reverse-proxy to the backend;
- ``GET  /v1/taskmanagement/task/{taskId}`` → task record (404 unknown);
- ``GET  /metrics``, ``GET /healthz``.
"""

from __future__ import annotations

import asyncio
import logging
import math
from dataclasses import dataclass

import aiohttp
from aiohttp import web

from ..metrics import DEFAULT_REGISTRY, MetricsRegistry
from ..utils.backends import normalize_backends, pick_backend
from ..taskstore import APITask, InMemoryTaskStore, TaskNotFound
from ..utils.http import SessionHolder

log = logging.getLogger("ai4e_tpu.gateway")


@dataclass
class Route:
    """One published API. ``prefix`` is the public path; async routes create
    tasks, sync routes proxy to ``backend_uri`` (VirtualService rewrite
    semantics, ``APIs/Charts/templates/routing.yml:1-28``)."""

    prefix: str
    mode: str  # "sync" | "async"
    backend_uri: str = ""  # sync: proxy target; async: recorded task endpoint
    # Weighted backend set for sync routes (canary; utils/backends.py);
    # [(backend_uri, 1.0)] for the plain single-backend case.
    backends: list = None
    # None = use the gateway's cap at request time; 0 = explicitly unlimited.
    max_body_bytes: int | None = None


class Gateway:
    def __init__(self, store: InMemoryTaskStore,
                 metrics: MetricsRegistry | None = None,
                 api_keys: set[str] | None = None,
                 max_body_bytes: int = 128 * 1024 * 1024):
        # Edge payload cap (the reference enforces limits at APIM, before
        # anything is stored): an async POST over the limit is refused with
        # 413 BEFORE a task (and its journaled ORIG body) is created;
        # per-route overrides via add_*_route(max_body_bytes=...).
        self.max_body_bytes = max_body_bytes
        self.store = store
        self.metrics = metrics or DEFAULT_REGISTRY
        self.routes: list[Route] = []
        self._requests = self.metrics.counter(
            "ai4e_gateway_requests_total", "Gateway requests by route/outcome")
        # Proxy fan-out is bounded by inbound connections, not the pool.
        self._sessions = SessionHolder(limit=0)
        # task_id -> {(loop, Event)} long-poll waiters (see _task).
        self._waiters: dict[str, set] = {}
        # Subscription-key auth (the reference's APIM front door requires
        # Ocp-Apim-Subscription-Key on every published API). None → open.
        self._api_keys = set(api_keys) if api_keys else None
        # Per-key rate limiting (APIM product throttling); None → unlimited.
        self._rate_limiter = None
        # Per-key request quotas (APIM product quota); None → unlimited.
        self._quota_tracker = None
        if hasattr(store, "add_listener"):
            store.add_listener(self._on_task_change)

        # aiohttp's own cap is effectively disabled: _read_limited enforces
        # the per-route edge cap incrementally (bounded buffering), and an
        # explicit 0 (unlimited) must actually mean unlimited.
        self.app = web.Application(client_max_size=1024**4,
                                   middlewares=[self._auth_middleware])
        self.app.router.add_get("/v1/taskmanagement/task/{task_id}", self._task)
        self.app.router.add_get("/healthz", self._health)
        self.app.router.add_get("/metrics", self._metrics)
        self.app.on_cleanup.append(self._cleanup)

    def set_api_keys(self, keys: set[str] | None) -> None:
        """Enable (or clear) subscription-key auth on the public surface."""
        self._api_keys = set(keys) if keys else None

    def set_rate_limiter(self, limiter) -> None:
        """Enable (or clear with None) per-key request-rate throttling on
        the published surface — the APIM product-throttling slot
        (``gateway/ratelimit.py``). Applies to published APIs and task
        polling; NOT to the internal task-store surface riding this app
        (throttling workers' status updates would stall the data plane the
        limiter is protecting)."""
        self._rate_limiter = limiter

    def set_quota_tracker(self, tracker) -> None:
        """Enable (or clear with None) per-key request QUOTAS — APIM's
        longer-horizon product cap beside the rate throttle. Same scope as
        the rate limiter; exhaustion answers 403 (APIM's quota status)
        with Retry-After = the window reset."""
        self._quota_tracker = tracker

    @web.middleware
    async def _auth_middleware(self, request: web.Request, handler):
        """Subscription-key gate — the APIM front-door behavior (every
        reference API call carries ``Ocp-Apim-Subscription-Key``). When keys
        are set, EVERYTHING on this app except health/metrics requires one —
        including the task-store surface when it rides this port (an open
        ``/v1/taskstore/*`` beside a keyed public API would hand out the
        same task data the 401 just protected); workers attach the key via
        ``AI4E_SERVICE_TASKSTORE_API_KEY``.
        """
        exempt = (request.path in ("/healthz", "/metrics"))
        key = (request.headers.get("Ocp-Apim-Subscription-Key")
               or request.headers.get("X-Api-Key"))
        if self._api_keys is not None and not exempt:
            if key not in self._api_keys:
                # Constant label: the path is attacker-chosen and would
                # grow metric cardinality without bound.
                self._requests.inc(route="unauthorized", outcome="401")
                return web.json_response(
                    {"error": "missing or invalid subscription key"},
                    status=401)
        throttled = ((self._rate_limiter is not None
                      or self._quota_tracker is not None)
                     and not exempt
                     and not request.path.startswith("/v1/taskstore/"))
        if throttled:
            # Bucket by the subscription key ONLY when auth validated it
            # (above) — with auth off the header is attacker-chosen and
            # rotating it would mint a fresh bucket per request; bucket by
            # caller address instead.
            identity = (key if self._api_keys is not None
                        else (request.remote or "anonymous"))
            # Quota PEEK first (non-consuming): an exhausted key gets the
            # 403 with its window-reset Retry-After without burning rate
            # tokens it would need once the window rolls.
            if self._quota_tracker is not None:
                allowed, retry_after = self._quota_tracker.would_allow(
                    identity)
                if not allowed:
                    self._requests.inc(route="throttled", outcome="403")
                    return web.json_response(
                        {"error": "quota exceeded"}, status=403,
                        headers={"Retry-After":
                                 str(max(1, math.ceil(retry_after)))})
            if self._rate_limiter is not None:
                allowed, retry_after = self._rate_limiter.allow(identity)
                if not allowed:
                    # A rate-refused request has consumed no quota (the
                    # peek above doesn't count).
                    self._requests.inc(route="throttled", outcome="429")
                    return web.json_response(
                        {"error": "rate limit exceeded"}, status=429,
                        # RFC 7231 delta-seconds: integer, minimum 1.
                        headers={"Retry-After":
                                 str(max(1, math.ceil(retry_after)))})
            if self._quota_tracker is not None:
                self._quota_tracker.allow(identity)  # consume the unit
        return await handler(request)

    def add_async_route(self, prefix: str, task_endpoint: str,
                        max_body_bytes: int | None = None) -> None:
        """Register an async API: requests become tasks addressed to
        ``task_endpoint`` (the backend route the dispatcher will POST to).
        ``max_body_bytes``: per-route edge cap (None → the gateway's)."""
        route = Route(prefix=prefix.rstrip("/"), mode="async",
                      backend_uri=task_endpoint,
                      max_body_bytes=max_body_bytes)
        self.routes.append(route)
        self.app.router.add_post(route.prefix, self._make_async_handler(route))
        self.app.router.add_post(route.prefix + "/{tail:.*}",
                                 self._make_async_handler(route))

    def add_sync_route(self, prefix: str, backend_uri,
                       max_body_bytes: int | None = None) -> None:
        backends = [(u.rstrip("/"), w)
                    for u, w in normalize_backends(backend_uri)]
        route = Route(prefix=prefix.rstrip("/"), mode="sync",
                      backend_uri=backends[0][0],
                      backends=backends,
                      max_body_bytes=max_body_bytes)
        self.routes.append(route)
        handler = self._make_sync_handler(route)
        for pattern in (route.prefix, route.prefix + "/{tail:.*}"):
            self.app.router.add_route("*", pattern, handler)

    # -- async: edge task creation (request_policy.xml:8-28) ---------------

    def _route_limit(self, route: Route) -> int:
        """The route's effective edge cap, resolved at request time so a
        gateway-level cap set after routes were registered still applies."""
        return (self.max_body_bytes if route.max_body_bytes is None
                else route.max_body_bytes)

    async def _read_limited(self, request: web.Request,
                            route: Route) -> bytes | None:
        """Body within the route's edge cap, else None (→ 413)."""
        from ..utils.http import read_body_limited
        return await read_body_limited(request, self._route_limit(route))

    def _payload_too_large(self, route: Route) -> web.Response:
        self._requests.inc(route=route.prefix, outcome="413")
        return web.Response(
            status=413,
            text=f"Payload exceeds {self._route_limit(route)} bytes.")

    def _make_async_handler(self, route: Route):
        async def handler(request: web.Request) -> web.Response:
            body = await self._read_limited(request, route)
            if body is None:
                return self._payload_too_large(route)
            # Record the full target: base backend URI + operation tail +
            # query, so the dispatcher can reproduce the exact call (the
            # reference stores the original request URI as Endpoint,
            # request_policy.xml:15).
            endpoint = route.backend_uri
            tail = request.match_info.get("tail", "")
            if tail:
                endpoint = endpoint.rstrip("/") + "/" + tail
            if request.query_string:
                endpoint += "?" + request.query_string
            from ..observability import get_tracer
            from ..taskstore import NotPrimaryError
            with get_tracer().span("create_task", route=route.prefix,
                                   headers=request.headers) as span:
                try:
                    task = self.store.upsert(APITask(
                        endpoint=endpoint,
                        body=body,
                        content_type=request.content_type or "application/json",
                        publish=True,
                    ))
                except NotPrimaryError:
                    # Standby control plane: reads are served here, task
                    # creation belongs to the primary — tell the client to
                    # retry (the LB/DNS flips after failover promotion).
                    self._requests.inc(route=route.prefix,
                                       outcome="not_primary")
                    return web.json_response(
                        {"error": "standby replica; task creation is on "
                                  "the primary"},
                        status=503,
                        # Same marker as the store surface: clients with a
                        # replica list rotate ONLY on this header — a plain
                        # overload 503 must never re-home them (ADVICE r4).
                        headers={"Retry-After": "2", "X-Not-Primary": "1"})
                span.task_id = task.task_id
            stored = self.store.get(task.task_id)
            outcome = "failed" if stored.canonical_status == "failed" else "created"
            self._requests.inc(route=route.prefix, outcome=outcome)
            return web.json_response(stored.to_dict())

        return handler

    # -- sync: reverse proxy (request_backend_policy.xml:1-6) --------------

    def _make_sync_handler(self, route: Route):
        async def handler(request: web.Request) -> web.Response:
            tail = request.match_info.get("tail", "")
            # Weighted per-request pick over the route's backend set
            # (single-backend routes skip the RNG) — Istio's weighted
            # VirtualService subsets, at the gateway.
            base = pick_backend(route.backends)
            target = base + (("/" + tail) if tail else "")
            if request.query_string:
                target += "?" + request.query_string
            body = await self._read_limited(request, route)
            if body is None:
                return self._payload_too_large(route)
            session = await self._get_session()
            try:
                async with session.request(
                    request.method, target, data=body,
                    # Strip hop headers AND the gateway credential: a sync
                    # backend (arbitrary URI, possibly third-party) must
                    # never see the subscription key it could replay against
                    # the keyed public surface.
                    headers={k: v for k, v in request.headers.items()
                             if k.lower() not in (
                                 "host", "content-length",
                                 "ocp-apim-subscription-key", "x-api-key")},
                ) as resp:
                    payload = await resp.read()
                    self._requests.inc(route=route.prefix, outcome=str(resp.status))
                    return web.Response(
                        status=resp.status, body=payload,
                        content_type=resp.content_type)
            except aiohttp.ClientError as exc:
                self._requests.inc(route=route.prefix, outcome="unreachable")
                return web.Response(status=502, text=f"Backend unreachable: {exc}")

        return handler

    # -- task polling (task_management_policy.xml:3-7) ---------------------

    MAX_LONG_POLL = 60.0

    async def _task(self, request: web.Request) -> web.Response:
        """Task status; ``?wait=SECONDS`` long-polls until the task reaches a
        terminal state (or the wait expires) instead of making the client
        spin on 5 ms GETs — the reference's polling contract
        (``GET /task/{taskId}``) with the poll storm removed. Event-driven:
        the store's change listener wakes exactly the waiters for that task.
        """
        task_id = request.match_info["task_id"]
        try:
            task = self.store.get(task_id)
        except TaskNotFound:
            return web.Response(status=404, text="Task not found.")

        wait = 0.0
        if "wait" in request.query:
            try:
                wait = min(float(request.query["wait"]), self.MAX_LONG_POLL)
            except ValueError:
                return web.Response(status=400, text="Bad wait parameter.")

        if wait > 0 and task.canonical_status not in ("completed", "failed"):
            # Register the waiter BEFORE the re-read so a transition between
            # re-read and wait() still sets the event (no lost wakeup).
            event = self._waiter_for(task_id)
            try:
                task = self.store.get(task_id)
                if task.canonical_status not in ("completed", "failed"):
                    try:
                        await asyncio.wait_for(event.wait(), timeout=wait)
                    except asyncio.TimeoutError:
                        pass
                    task = self.store.get(task_id)
            except TaskNotFound:
                # Retention evicted the task mid-wait (tight retention
                # config) — answer like any unknown task, not with a 500.
                return web.Response(status=404, text="Task not found.")
            finally:
                self._drop_waiter(task_id, event)
        return web.json_response(task.to_dict())

    # Waiter bookkeeping is copy-on-write (sets are replaced, never mutated):
    # _on_task_change may iterate from any thread while the event loop
    # registers/drops waiters, and an in-place add() during iteration would
    # raise — swallowed by the store's _notify — losing the wakeup.

    def _waiter_for(self, task_id: str) -> asyncio.Event:
        event = asyncio.Event()
        self._waiters[task_id] = self._waiters.get(task_id, frozenset()) | {
            (asyncio.get_running_loop(), event)}
        return event

    def _drop_waiter(self, task_id: str, event: asyncio.Event) -> None:
        entries = self._waiters.get(task_id)
        if entries:
            remaining = frozenset(e for e in entries if e[1] is not event)
            if remaining:
                self._waiters[task_id] = remaining
            else:
                del self._waiters[task_id]

    def _on_task_change(self, task) -> None:
        """Store listener — may fire from any thread; wake that task's
        long-poll waiters on terminal transitions."""
        if task.canonical_status not in ("completed", "failed"):
            return
        for loop, event in self._waiters.get(task.task_id, frozenset()):
            loop.call_soon_threadsafe(event.set)

    async def _health(self, _: web.Request) -> web.Response:
        return web.json_response({"status": "healthy", "routes": len(self.routes)})

    async def _metrics(self, _: web.Request) -> web.Response:
        return web.Response(text=self.metrics.render_prometheus(),
                            content_type="text/plain")

    async def _get_session(self) -> aiohttp.ClientSession:
        return await self._sessions.get()

    async def _cleanup(self, _app) -> None:
        await self._sessions.close()

    def run(self, host: str = "0.0.0.0", port: int = 8080) -> None:
        web.run_app(self.app, host=host, port=port)
