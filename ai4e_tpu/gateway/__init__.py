from .router import Gateway, Route

__all__ = ["Gateway", "Route"]
