from .registration import (
    ApiDefinition,
    load_definitions,
    register_definitions,
    routes_from_definitions,
)
from .router import Gateway, Route

__all__ = [
    "ApiDefinition",
    "Gateway",
    "Route",
    "load_definitions",
    "register_definitions",
    "routes_from_definitions",
]
