"""The soak engine — ``scripts/soak.sh``'s body, moved onto the rig's
process supervision (ISSUE 11 satellite).

The bash script used to hand-roll exactly what ``Supervisor`` now owns:
wait for a previous run's ports and SIGKILL-escalate on whatever still
holds them, health-gate both children, trap-kill on every exit path. The
script keeps its CLI contract (``scripts/soak.sh [minutes] [outdir]``)
as a thin wrapper over ``python -m ai4e_tpu.rig soak``; the windowed
closed-loop measurement and the RSS-creep watch are unchanged.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import subprocess
import sys
import time

from ..observability.vitals import read_rss_mb
from .supervisor import Supervisor, python_argv

log = logging.getLogger("ai4e_tpu.rig.soak")

CP_PORT = 18889
WK_PORT = 18890


def _rss_mb(pid: int | None) -> float:
    """Child RSS via the shared vitals parser. The None guard is
    load-bearing: a vanished child's pid is None, and the helper's
    pid=None means '/proc/self' — without the guard a dead child would
    read as the soak DRIVER's own RSS and the death check below
    (`< 0` breaks the loop) would never fire."""
    return read_rss_mb(pid) if pid is not None else -1.0


def _write_specs(out: str) -> None:
    with open(os.path.join(out, "routes.json"), "w",
              encoding="utf-8") as fh:
        json.dump({"apis": [{
            "prefix": "/v1/echo/run-async",
            "backend": f"http://127.0.0.1:{WK_PORT}/v1/echo/run-async",
            "concurrency": 4, "retry_delay": 0.2}]}, fh)
    with open(os.path.join(out, "models.json"), "w",
              encoding="utf-8") as fh:
        json.dump({"service_name": "soak-echo", "prefix": "v1/echo",
                   "taskstore": f"http://127.0.0.1:{CP_PORT}",
                   "models": [{"family": "echo", "name": "echo",
                               "size": 16, "buckets": [8],
                               "async_path": "/run-async"}]}, fh)
    import io

    import numpy as np
    buf = io.BytesIO()
    np.save(buf, np.arange(16, dtype=np.float32))
    with open(os.path.join(out, "payload.npy"), "wb") as fh:
        fh.write(buf.getvalue())


async def run_soak(minutes: float = 10.0, out: str = "/tmp/soak") -> int:
    os.makedirs(out, exist_ok=True)
    _write_specs(out)
    env = {**os.environ,
           "AI4E_RUNTIME_PLATFORM": "cpu",
           "AI4E_PLATFORM_RETRY_DELAY": "0.2"}
    windows: list[dict] = []
    failures = 0
    with Supervisor() as sup:
        sup.spawn("control-plane",
                  python_argv("ai4e_tpu", "control-plane", "--routes",
                              os.path.join(out, "routes.json"),
                              "--port", str(CP_PORT)),
                  env={**env, "AI4E_PLATFORM_JOURNAL_PATH":
                       os.path.join(out, "tasks.jsonl")},
                  log_path=os.path.join(out, "cp.log"), port=CP_PORT,
                  health_url=f"http://127.0.0.1:{CP_PORT}/healthz")
        sup.spawn("worker",
                  python_argv("ai4e_tpu", "worker", "--models",
                              os.path.join(out, "models.json"),
                              "--port", str(WK_PORT)),
                  env=env, log_path=os.path.join(out, "wk.log"),
                  port=WK_PORT,
                  health_url=f"http://127.0.0.1:{WK_PORT}/v1/echo/")
        sup.wait_healthy("control-plane", timeout=120.0)
        sup.wait_healthy("worker", timeout=240.0)
        cp_pid, wk_pid = (sup.children["control-plane"].pid,
                          sup.children["worker"].pid)

        deadline = time.time() + minutes * 60.0
        while time.time() < deadline:
            run = await asyncio.to_thread(
                subprocess.run,
                [sys.executable, "examples/loadgen.py",
                 "--gateway", f"http://127.0.0.1:{CP_PORT}",
                 "--path", "/v1/echo/run-async",
                 "--payload", os.path.join(out, "payload.npy"),
                 "--mode", "async", "--concurrency", "32",
                 "--duration", "30", "--ramp", "2"],
                capture_output=True, text=True, timeout=300)
            line = (run.stdout.strip().splitlines()[-1]
                    if run.stdout.strip() else "{}")
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                rec = {"error": line[:200]}
            rec["cp_rss_mb"] = _rss_mb(cp_pid)
            rec["wk_rss_mb"] = _rss_mb(wk_pid)
            windows.append(rec)
            failures += int(rec.get("failed", 0) or 0)
            print(json.dumps(rec), flush=True)
            if rec["cp_rss_mb"] < 0 or rec["wk_rss_mb"] < 0:
                break

    rss = [(w["cp_rss_mb"], w["wk_rss_mb"]) for w in windows]
    summary = {
        "soak_minutes": minutes,
        "windows": len(windows),
        "total_completed": sum(int(w.get("completed", 0) or 0)
                               for w in windows),
        "total_failed": failures,
        "throughput_first": windows[0].get("value") if windows else None,
        "throughput_last": windows[-1].get("value") if windows else None,
        "cp_rss_first_mb": rss[0][0] if rss else None,
        "cp_rss_last_mb": rss[-1][0] if rss else None,
        "wk_rss_first_mb": rss[0][1] if rss else None,
        "wk_rss_last_mb": rss[-1][1] if rss else None,
        "process_death": any(a < 0 or b < 0 for a, b in rss),
    }
    print(json.dumps(summary), flush=True)
    with open(os.path.join(out, "soak_summary.json"), "w",
              encoding="utf-8") as fh:
        json.dump({"summary": summary, "windows": windows}, fh, indent=1)
    ok = (not summary["process_death"] and failures == 0
          and summary["windows"] > 0)
    return 0 if ok else 1
