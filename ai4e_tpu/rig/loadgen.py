"""Loadgen PROCESS — one open-loop traffic source through the balancer.

Each loadgen drives ``rate / loadgens`` request starts per second with
``utils.loadclient.run_open_loop`` (the clock schedules arrivals, so a
slow platform faces the same offered rate as a fast one and the shortfall
is REPORTED — offered vs achieved plus the client error taxonomy — never
silently re-labeled as the target). Beside the window JSON it records:

- every accepted TaskId and every client-observed terminal status — the
  rig verdict's reconciliation input;
- a 1 Hz sample curve of offered/accepted/terminal counts with wall-clock
  timestamps, which the driver joins against the chaos timeline to plot
  goodput during and after each fault.
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import time

from ..utils.loadclient import run_open_loop
from .topology import Topology

log = logging.getLogger("ai4e_tpu.rig.loadgen")


async def run_loadgen(topo: Topology, index: int) -> None:
    import aiohttp

    base = topo.balancer_url()
    payload = json.dumps(
        {"loadgen": index,
         "pad": "x" * max(0, topo.payload_bytes - 32)}).encode("utf-8")
    headers = {"Content-Type": "application/json"}
    rate = topo.rate / max(1, topo.loadgens)
    tenant = None
    if index < len(topo.loadgen_tenants):
        # Tenant-pinned loadgen: ONE tenant's whole traffic stream, so
        # the window's error taxonomy (tenant_quota_429 vs backpressure)
        # IS that tenant's shed tally and the noisy-neighbor A/B reads
        # straight off the per-loadgen artifacts.
        assignment = topo.loadgen_tenants[index]
        tenant = assignment.get("name")
        headers["Ocp-Apim-Subscription-Key"] = assignment["key"]
        rate = float(assignment.get("rate", rate))
    accepted: list[str] = []
    terminal: dict[str, str] = {}
    samples: list[dict] = []

    def status_url_for(task_id: str) -> str:
        return f"{base}/v1/taskmanagement/task/{task_id}"

    started_at = time.time()
    done = asyncio.Event()

    async def sampler() -> None:
        while not done.is_set():
            samples.append({
                "t": round(time.time(), 2),
                "accepted": len(accepted),
                "terminal": len(terminal),
                "completed": sum(1 for s in terminal.values()
                                 if "completed" in s),
            })
            try:
                await asyncio.wait_for(done.wait(), 1.0)
            except asyncio.TimeoutError:
                continue

    sampler_task = asyncio.create_task(sampler())
    async with aiohttp.ClientSession(
            timeout=aiohttp.ClientTimeout(total=90),
            connector=aiohttp.TCPConnector(limit=0)) as session:
        window = await run_open_loop(
            session,
            post_url=base + topo.route,
            payload=payload,
            headers=headers,
            rate=rate,
            status_url_for=status_url_for,
            duration=topo.duration,
            ramp=topo.ramp,
            max_inflight=topo.max_inflight,
            task_timeout=topo.task_timeout,
            poll_wait=topo.poll_wait,
            on_accepted=accepted.append,
            on_terminal=terminal.__setitem__,
        )
    done.set()
    await sampler_task

    out = {
        "loadgen": index,
        **({"tenant": tenant} if tenant else {}),
        "started_at": started_at,
        "finished_at": time.time(),
        "window": window,
        "samples": samples,
        "accepted": accepted,
        "terminal": terminal,
    }
    path = os.path.join(topo.workdir, f"loadgen-{index}.json")
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(out, fh)
    os.replace(tmp, path)  # atomic: the driver must never read a torn file
    log.info("loadgen %d: offered %.0f/s achieved %.0f/s (%d accepted, "
             "%d terminal)", index, window["offered_rate"],
             window["achieved_rate"], len(accepted), len(terminal))
