"""Per-role vitals attachment — every rig process samples its own
runtime vitals (``observability/vitals.py``) into its per-role registry
and serves the recent-sample ring at ``GET /v1/debug/vitals``, which the
driver collects pre-teardown for the Perfetto timeline's counter tracks
(``observability/timeline.py``). One helper so all six roles wire it
identically."""

from __future__ import annotations

from aiohttp import web

from ..metrics import MetricsRegistry
from ..observability.vitals import VitalsSampler
from .topology import Topology

VITALS_PATH = "/v1/debug/vitals"


def attach_vitals(app: web.Application, topo: Topology,
                  metrics: MetricsRegistry) -> VitalsSampler | None:
    """Create a sampler on the role's registry, register the dump route,
    and tie the sample loop to the app's lifecycle. Call BEFORE any
    catch-all route is added (the balancer's proxy tail). No-op when the
    topology runs observability-off: ``--no-observability`` means a
    telemetry-free fleet — no sampler task, no route, no
    ``ai4e_process_*`` series — byte-identical to the PR 11 roles."""
    if not topo.observability:
        return None
    sampler = VitalsSampler(metrics=metrics,
                            interval_s=topo.vitals_interval)

    async def vitals_route(_: web.Request) -> web.Response:
        return web.json_response({"recent": sampler.recent()})

    app.router.add_get(VITALS_PATH, vitals_route)

    async def _start(_app) -> None:
        await sampler.start()

    async def _stop(_app) -> None:
        await sampler.stop()

    app.on_startup.append(_start)
    app.on_cleanup.append(_stop)
    return sampler
