"""Dispatcher PROCESS — one shard's queue drainer over the wire.

The unmodified ``broker.Dispatcher`` (with its full duplicate-suppression
/ backpressure / dead-letter semantics) running against:

- ``WireBroker`` — leases popped from the shard store node's
  ``/v1/rig/broker/*`` surface (the lease lives server-side, so a SIGKILL
  of this process loses nothing: the lease expires and the message
  redelivers to another dispatcher — exactly the chaos verb the rig
  replays);
- ``RingStoreClient`` as the task manager — status writes ring-route by
  TaskId, so a task whose slot moved mid-delivery still lands its
  transition on the owning shard;
- the shard's CPU-echo worker set as resilient weighted backends
  (connect-failover between workers, terminal-probe duplicate
  suppression on redeliveries).
"""

from __future__ import annotations

import logging

from aiohttp import web

from ..broker.dispatcher import Dispatcher
from ..metrics import MetricsRegistry
from ..resilience import BackendHealth, ResiliencePolicy
from ..rollout.canary import CanaryWeights
from ..taskstore import endpoint_path
from .topology import Topology
from .wire import RingStoreClient, WireBroker

log = logging.getLogger("ai4e_tpu.rig.dispatcher")


async def run_dispatchernode(topo: Topology, shard: int, index: int) -> None:
    from .supervisor import serve_until_signal

    metrics = MetricsRegistry()
    ring = RingStoreClient(topo.all_shard_urls(), slots=topo.slots)
    broker = WireBroker(topo.shard_urls(shard),
                        lease_seconds=topo.lease_seconds)
    health = BackendHealth(ResiliencePolicy(retry_base_s=0.05,
                                            retry_cap_s=1.0),
                           metrics=metrics)
    # Canary split (rollout/, docs/deployment.md#rollouts): the rolling-
    # upgrade driver POSTs generation assignments + the canary share to
    # /v1/rollout/weights; placement rescales the weighted worker pool
    # through the attached CanaryWeights on every pick.
    canary = CanaryWeights()
    health.attach_canary(canary)
    observability = None
    if topo.observability:
        # The hub's stamps (popped/delivered/retry/failover/...) ride
        # fire-and-forget wire appends to the owning shard node; its
        # store listener is inert here (the ring client's add_listener
        # is a no-op — terminal accounting lives on the shard nodes).
        from ..observability.hub import RequestObservability
        observability = RequestObservability(ring, metrics=metrics)
    dispatcher = Dispatcher(
        broker, endpoint_path(topo.route), topo.worker_urls(shard), ring,
        retry_delay=topo.retry_delay,
        concurrency=topo.dispatcher_concurrency,
        request_timeout=30.0, metrics=metrics, resilience=health,
        observability=observability)

    app = web.Application()

    async def health_route(_: web.Request) -> web.Response:
        return web.json_response({"status": "healthy", "shard": shard,
                                  "busy": dispatcher._busy})

    async def metrics_route(_: web.Request) -> web.Response:
        return web.Response(text=metrics.render_prometheus(),
                            content_type="text/plain")

    async def rollout_weights(request: web.Request) -> web.Response:
        try:
            body = await request.json()
            if not isinstance(body, dict):
                raise ValueError("body must be an object")
            for uri, gen in (body.get("generations") or {}).items():
                canary.set_generation(str(uri), int(gen))
            # Pre-restart eject / post-restart re-admit: the rollout
            # driver marks a backend draining BEFORE it drains + kills
            # the process (deliveries route to peers for the TTL, no
            # connect-error breaker trips from the restart window) and
            # resets it once the replacement answers /healthz.
            for uri, ttl in (body.get("draining") or {}).items():
                health.mark_draining(str(uri), float(ttl))
            for uri in body.get("undrain") or ():
                health.reset(str(uri))
            if body.get("clear"):
                canary.clear_split()
            elif body.get("canary_generation") is not None:
                canary.set_split(int(body["canary_generation"]),
                                 float(body.get("share", 0.0)))
        except (ValueError, TypeError) as exc:
            return web.json_response({"error": str(exc)}, status=400)
        generation, share = canary.split
        return web.json_response({"canary_generation": generation,
                                  "share": share})

    app.router.add_get("/healthz", health_route)
    app.router.add_get("/metrics", metrics_route)
    app.router.add_post("/v1/rollout/weights", rollout_weights)
    from .nodevitals import attach_vitals
    attach_vitals(app, topo, metrics)

    async def start(_app) -> None:
        await dispatcher.start()

    async def stop(_app) -> None:
        await dispatcher.stop()
        await broker.aclose()
        await ring.aclose()

    app.on_startup.append(start)
    app.on_cleanup.append(stop)
    await serve_until_signal(app, topo.host,
                             topo.dispatcher_port(shard, index))
