"""Topology spec — the one document every rig process derives itself from.

The driver resolves counts + the port layout once, writes the spec to
``<workdir>/topology.json``, and launches every child as
``python -m ai4e_tpu.rig <role> --spec <file> --shard i --index j``. A
child never guesses a peer's address: gateways compute the shard store
URL lists (primary first, then replicas — the rotation order every wire
client uses), dispatchers compute their shard's worker URLs, the
balancer computes the gateway URLs. Deterministic ports also make the
teardown verifiable: the supervisor can prove nothing it owns still
listens.

Port layout (``base_port`` from ``--base-port`` or ``AI4E_RIG_BASE_PORT``,
default 18800; all on ``host``):

- balancer:          base
- gateway g:         base + 1 + g
- shard s primary:   base + 20 + s
- shard s replica r: base + 40 + s * replicas_max + r
- dispatcher d of s: base + 60 + s * dispatchers_max + d  (health/metrics)
- worker w of s:     base + 80 + s * workers_max + w
"""

from __future__ import annotations

import dataclasses
import json
import os
from dataclasses import dataclass, field

ECHO_ROUTE = "/v1/echo/run-async"

# Sub-range strides: bounded so layouts stay stable as counts vary.
_REPLICAS_MAX = 4
_DISPATCHERS_MAX = 4
_WORKERS_MAX = 4


@dataclass
class Topology:
    gateways: int = 3
    shards: int = 2
    replicas: int = 1          # per shard
    dispatchers: int = 1       # per shard (separate OS processes)
    workers: int = 1           # per shard (CPU echo processes)
    loadgens: int = 2
    slots: int = 16            # hash-slot table size (stable_hash % slots)
    rate: float = 10000.0      # offered req/s, total across loadgens
    duration: float = 30.0     # measured window per loadgen (s)
    ramp: float = 3.0
    max_inflight: int = 512    # per loadgen process
    task_timeout: float = 60.0
    poll_wait: float = 20.0
    dispatcher_concurrency: int = 8
    lease_seconds: float = 5.0   # short: a killed dispatcher's leases must
                                 # redeliver within the run, not in 5 min
    retry_delay: float = 0.2
    work_ms: float = 0.0       # artificial per-request worker time
    chaos: bool = True
    seed: int = 20260803
    host: str = "127.0.0.1"
    base_port: int = 18800
    workdir: str = "/tmp/ai4e-rig"
    route: str = ECHO_ROUTE
    payload_bytes: int = 64
    extra: dict = field(default_factory=dict)

    def __post_init__(self):
        if self.gateways < 1 or self.shards < 1:
            raise ValueError("topology needs >= 1 gateway and >= 1 shard")
        if not (1 <= self.replicas <= _REPLICAS_MAX):
            raise ValueError(f"replicas must be 1..{_REPLICAS_MAX}")
        if not (1 <= self.dispatchers <= _DISPATCHERS_MAX):
            raise ValueError(f"dispatchers must be 1..{_DISPATCHERS_MAX}")
        if not (1 <= self.workers <= _WORKERS_MAX):
            raise ValueError(f"workers must be 1..{_WORKERS_MAX}")
        if self.slots < self.shards:
            raise ValueError("slots must be >= shards")

    # -- ports/urls ---------------------------------------------------------

    def balancer_port(self) -> int:
        return self.base_port

    def gateway_port(self, g: int) -> int:
        return self.base_port + 1 + g

    def shard_port(self, s: int) -> int:
        return self.base_port + 20 + s

    def replica_port(self, s: int, r: int) -> int:
        return self.base_port + 40 + s * _REPLICAS_MAX + r

    def dispatcher_port(self, s: int, d: int) -> int:
        return self.base_port + 60 + s * _DISPATCHERS_MAX + d

    def worker_port(self, s: int, w: int) -> int:
        return self.base_port + 80 + s * _WORKERS_MAX + w

    def _url(self, port: int) -> str:
        return f"http://{self.host}:{port}"

    def balancer_url(self) -> str:
        return self._url(self.balancer_port())

    def gateway_urls(self) -> list[str]:
        return [self._url(self.gateway_port(g)) for g in range(self.gateways)]

    def shard_urls(self, s: int) -> list[str]:
        """Store URL list for shard ``s`` — primary FIRST, then replicas:
        the rotation order every wire client (gateway, dispatcher, worker,
        feed tail) walks on connect errors / 503-not-primary, which is
        what re-homes the whole fleet onto a promoted replica."""
        return [self._url(self.shard_port(s))] + [
            self._url(self.replica_port(s, r)) for r in range(self.replicas)]

    def all_shard_urls(self) -> list[list[str]]:
        return [self.shard_urls(s) for s in range(self.shards)]

    def worker_urls(self, s: int) -> list[str]:
        return [self._url(self.worker_port(s, w)) + self.route
                for w in range(self.workers)]

    def journal_path(self, s: int) -> str:
        return os.path.join(self.workdir, f"shard{s}.jsonl")

    def replica_journal_path(self, s: int, r: int) -> str:
        return os.path.join(self.workdir, f"shard{s}.replica{r}.jsonl")

    def all_ports(self) -> list[int]:
        ports = [self.balancer_port()]
        ports += [self.gateway_port(g) for g in range(self.gateways)]
        for s in range(self.shards):
            ports.append(self.shard_port(s))
            ports += [self.replica_port(s, r) for r in range(self.replicas)]
            ports += [self.dispatcher_port(s, d)
                      for d in range(self.dispatchers)]
            ports += [self.worker_port(s, w) for w in range(self.workers)]
        return ports

    # -- (de)serialization --------------------------------------------------

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "Topology":
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in known})

    def save(self, path: str) -> None:
        with open(path, "w", encoding="utf-8") as fh:
            json.dump(self.to_dict(), fh, indent=1)

    @classmethod
    def load(cls, path: str) -> "Topology":
        with open(path, encoding="utf-8") as fh:
            return cls.from_dict(json.load(fh))

    def spec_path(self) -> str:
        return os.path.join(self.workdir, "topology.json")
