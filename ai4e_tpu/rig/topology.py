"""Topology spec — the one document every rig process derives itself from.

The driver resolves counts + the port layout once, writes the spec to
``<workdir>/topology.json``, and launches every child as
``python -m ai4e_tpu.rig <role> --spec <file> --shard i --index j``. A
child never guesses a peer's address: gateways compute the shard store
URL lists (primary first, then replicas — the rotation order every wire
client uses), dispatchers compute their shard's worker URLs, the
balancer computes the gateway URLs. Deterministic ports also make the
teardown verifiable: the supervisor can prove nothing it owns still
listens.

Port layout (``base_port`` from ``--base-port`` or ``AI4E_RIG_BASE_PORT``,
default 18800; all on ``host``):

- balancer:          base
- gateway g:         base + 1 + g      (g bounded by the collector slot)
- collector:         base + 19         (fleet telemetry, docs/deployment.md)
- shard s primary:   base + 20 + s
- shard s replica r: base + 40 + s * replicas_max + r
- dispatcher d of s: base + 60 + s * dispatchers_max + d  (health/metrics)
- worker w of s:     base + 80 + s * workers_max + w
"""

from __future__ import annotations

import dataclasses
import json
import os
from dataclasses import dataclass, field

ECHO_ROUTE = "/v1/echo/run-async"

# Sub-range strides: bounded so layouts stay stable as counts vary.
_REPLICAS_MAX = 4
_DISPATCHERS_MAX = 4
_WORKERS_MAX = 4


@dataclass
class Topology:
    gateways: int = 3
    shards: int = 2
    replicas: int = 1          # per shard
    dispatchers: int = 1       # per shard (separate OS processes)
    workers: int = 1           # per shard (CPU echo processes)
    loadgens: int = 2
    slots: int = 16            # hash-slot table size (stable_hash % slots)
    rate: float = 10000.0      # offered req/s, total across loadgens
    duration: float = 30.0     # measured window per loadgen (s)
    ramp: float = 3.0
    max_inflight: int = 512    # per loadgen process
    task_timeout: float = 60.0
    poll_wait: float = 20.0
    dispatcher_concurrency: int = 8
    lease_seconds: float = 5.0   # short: a killed dispatcher's leases must
                                 # redeliver within the run, not in 5 min
    retry_delay: float = 0.2
    work_ms: float = 0.0       # artificial per-request worker time
    chaos: bool = True
    observability: bool = True  # hop-ledger stamps + flight rings per role
    collector: bool = True     # fleet-telemetry collector process
    scrape_interval: float = 2.0   # collector scrape period (s)
    vitals_interval: float = 1.0   # per-role vitals sample period (s)
    seed: int = 20260803
    host: str = "127.0.0.1"
    base_port: int = 18800
    workdir: str = "/tmp/ai4e-rig"
    route: str = ECHO_ROUTE
    payload_bytes: int = 64
    # Multi-tenancy (tenancy/, docs/tenancy.md). ``tenants`` is the
    # registry spec ("name=key:weight:rps:burst,..."): non-empty puts the
    # tenant resolver + token-bucket quota on EVERY gateway replica (each
    # enforces the contracted rps locally, so the fleet ceiling is
    # gateways × rps — the per-instance rate-limit semantic, stated in
    # docs/tenancy.md) and weighted-fair lanes on every shard broker.
    # ``loadgen_tenants[i]`` pins loadgen i to one tenant:
    # {"name": ..., "key": ..., "rate": rps} — rate overrides the even
    # rate/loadgens split, which is how the noisy-neighbor scenario
    # drives one tenant at 10× while the victims hold rated.
    tenants: str = ""
    loadgen_tenants: list = field(default_factory=list)
    # Mesh serving plane (runtime/mesh/, docs/mesh_serving.md). ``mesh``
    # is the declarative layout spec ("dp=8", "dp=2,tp=2"): non-empty
    # boots every worker as a MESH endpoint — the JAX-free half of the
    # production MeshEndpoint (same spec grammar, same EndpointHealth/
    # MeshCoordinator state machine), with the layout's cost-tier label
    # riding the route so every backend URI carries the substring the
    # orchestration cost map keys on. ``mesh_poison_nths`` injects
    # degradation: comma-separated 1-based delivery ordinals each worker
    # poisons (the rig analogue of AI4E_FAULT_MESH_POISON_NTHS — those
    # deliveries answer 503 result-invalidated and redeliver per task).
    # ``mesh_recovery_s`` is how long a flipped-unhealthy worker stays
    # dark (answering 500, ejected by dispatcher breakers) before its
    # "follower restart" probe delivery is allowed to heal it.
    mesh: str = ""
    mesh_poison_nths: str = ""
    mesh_recovery_s: float = 2.0
    # Zero-downtime rollout (rollout/, docs/deployment.md#rollouts).
    # ``rollout`` is the scenario: "" (off), "clean" (every generation
    # healthy — the upgrade must lose nothing and surface zero
    # client-visible 5xx from drained workers) or "bad-canary"
    # (``rollout_error_rate`` of deliveries fail with 500 at generations
    # >= ``rollout_bad_generation`` — the guard must auto-rollback before
    # the canary's traffic share passes 50%). The driver (rig/rollout.py)
    # starts after ``ramp``, drains + respawns workers one at a time with
    # a bumped AI4E_ROLLOUT_GENERATION, and steps canary weight through
    # ``rollout_steps`` holding ``rollout_hold_s`` per step.
    rollout: str = ""
    rollout_error_rate: float = 0.0
    rollout_bad_generation: int = 2
    rollout_steps: str = "25,50,100"
    rollout_hold_s: float = 3.0
    rollout_drain_timeout_ms: float = 5000.0
    extra: dict = field(default_factory=dict)

    def __post_init__(self):
        if self.mesh:
            from ..runtime.mesh import parse_mesh_spec
            layout = parse_mesh_spec(self.mesh)  # raises MeshSpecError early
            if layout is not None and self.route == ECHO_ROUTE:
                # Tier-labelled route: reloading a saved spec keeps the
                # already-derived route (it no longer equals ECHO_ROUTE).
                self.route = f"/v1/echo-{layout.tier_label}/run-async"
        if self.gateways < 1 or self.shards < 1:
            raise ValueError("topology needs >= 1 gateway and >= 1 shard")
        if self.gateways > 18:
            # Gateway g lives at base+1+g; g=17 (the 18th gateway) takes
            # base+18, the last slot before the collector's base+19.
            raise ValueError("gateways must be <= 18 (port layout)")
        if not (1 <= self.replicas <= _REPLICAS_MAX):
            raise ValueError(f"replicas must be 1..{_REPLICAS_MAX}")
        if not (1 <= self.dispatchers <= _DISPATCHERS_MAX):
            raise ValueError(f"dispatchers must be 1..{_DISPATCHERS_MAX}")
        if not (1 <= self.workers <= _WORKERS_MAX):
            raise ValueError(f"workers must be 1..{_WORKERS_MAX}")
        if self.slots < self.shards:
            raise ValueError("slots must be >= shards")
        if self.rollout not in ("", "clean", "bad-canary"):
            raise ValueError("rollout must be '', 'clean' or 'bad-canary'")
        if self.rollout == "bad-canary" and self.rollout_error_rate <= 0:
            # The scenario's whole point is a visibly bad generation.
            self.rollout_error_rate = 0.25

    # -- ports/urls ---------------------------------------------------------

    def balancer_port(self) -> int:
        return self.base_port

    def gateway_port(self, g: int) -> int:
        return self.base_port + 1 + g

    def collector_port(self) -> int:
        return self.base_port + 19

    def shard_port(self, s: int) -> int:
        return self.base_port + 20 + s

    def replica_port(self, s: int, r: int) -> int:
        return self.base_port + 40 + s * _REPLICAS_MAX + r

    def dispatcher_port(self, s: int, d: int) -> int:
        return self.base_port + 60 + s * _DISPATCHERS_MAX + d

    def worker_port(self, s: int, w: int) -> int:
        return self.base_port + 80 + s * _WORKERS_MAX + w

    def _url(self, port: int) -> str:
        return f"http://{self.host}:{port}"

    def balancer_url(self) -> str:
        return self._url(self.balancer_port())

    def gateway_urls(self) -> list[str]:
        return [self._url(self.gateway_port(g)) for g in range(self.gateways)]

    def shard_urls(self, s: int) -> list[str]:
        """Store URL list for shard ``s`` — primary FIRST, then replicas:
        the rotation order every wire client (gateway, dispatcher, worker,
        feed tail) walks on connect errors / 503-not-primary, which is
        what re-homes the whole fleet onto a promoted replica."""
        return [self._url(self.shard_port(s))] + [
            self._url(self.replica_port(s, r)) for r in range(self.replicas)]

    def all_shard_urls(self) -> list[list[str]]:
        return [self.shard_urls(s) for s in range(self.shards)]

    def worker_urls(self, s: int) -> list[str]:
        return [self._url(self.worker_port(s, w)) + self.route
                for w in range(self.workers)]

    def journal_path(self, s: int) -> str:
        return os.path.join(self.workdir, f"shard{s}.jsonl")

    def replica_journal_path(self, s: int, r: int) -> str:
        return os.path.join(self.workdir, f"shard{s}.replica{r}.jsonl")

    def collector_url(self) -> str:
        return self._url(self.collector_port())

    def metrics_urls(self) -> dict[str, str]:
        """Every scrapeable node, by proc name — the rig verdict's
        post-hoc merge and the live collector's target set share this
        one map (the collector excludes itself)."""
        urls = {"balancer": self.balancer_url()}
        for g in range(self.gateways):
            urls[f"gateway{g}"] = self.gateway_urls()[g]
        for s in range(self.shards):
            urls[f"store{s}"] = self.shard_urls(s)[0]
            for r in range(self.replicas):
                urls[f"store{s}r{r}"] = self.shard_urls(s)[1 + r]
            for d in range(self.dispatchers):
                urls[f"dispatcher{s}.{d}"] = \
                    self._url(self.dispatcher_port(s, d))
            for w in range(self.workers):
                urls[f"worker{s}.{w}"] = self._url(self.worker_port(s, w))
        if self.collector:
            urls["collector"] = self.collector_url()
        return urls

    def all_ports(self) -> list[int]:
        ports = [self.balancer_port()]
        if self.collector:
            ports.append(self.collector_port())
        ports += [self.gateway_port(g) for g in range(self.gateways)]
        for s in range(self.shards):
            ports.append(self.shard_port(s))
            ports += [self.replica_port(s, r) for r in range(self.replicas)]
            ports += [self.dispatcher_port(s, d)
                      for d in range(self.dispatchers)]
            ports += [self.worker_port(s, w) for w in range(self.workers)]
        return ports

    # -- (de)serialization --------------------------------------------------

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: dict) -> "Topology":
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in known})

    def save(self, path: str) -> None:
        with open(path, "w", encoding="utf-8") as fh:
            json.dump(self.to_dict(), fh, indent=1)

    @classmethod
    def load(cls, path: str) -> "Topology":
        with open(path, encoding="utf-8") as fh:
            return cls.from_dict(json.load(fh))

    def spec_path(self) -> str:
        return os.path.join(self.workdir, "topology.json")
