"""One shard's store PROCESS — journaled primary or wire-tailing replica.

This is the role the single-process ``ShardGroup`` becomes when the shard
boundary is a socket (docs/deployment.md). Each store node serves:

- the full task-store HTTP surface (``taskstore/http.py`` — upsert/update/
  task/result + the journal-stream replication surface), so gateways,
  dispatchers, workers and wire replicas all speak the contracts that
  already exist;
- ``GET  /v1/rig/feed``  — ndjson stream of this node's terminal task
  transitions (the wire form of ``ShardChangeFeed``; gateways tail it so a
  replica that did not admit a task still wakes its long-poll);
- ``GET  /v1/rig/slots`` — this node's slot-fence table (``{"fenced":
  {slot: owner|null}}``), what ring clients re-fetch after a 409
  ``X-Not-Owner``;
- ``POST /v1/rig/slots`` — fence propagation (the move driver tells
  sibling nodes about a flip so a later-promoted replica owns the right
  keyspace);
- ``POST /v1/rig/broker/pop`` / ``POST /v1/rig/broker/done`` — the wire
  broker surface dispatcher processes lease from (the queue itself lives
  HERE, beside the store whose publisher feeds it — a lease dies with the
  leasing dispatcher and redelivers server-side);
- ``POST /v1/rig/move_slot`` / ``POST /v1/rig/import`` — the live
  cross-process rebalance. Unlike the in-process ``move_slot`` (delta
  handoff under the source lock), the wire form fences the slot FIRST
  (writes 409 for the copy window — ring clients back off and retry),
  copies, then flips: a brief unavailability window instead of a
  two-shard lock nest, stated in docs/deployment.md.

A **replica** node tails its primary's journal stream with the wire-mode
``ShardReplicaLink`` and runs a watchdog: once the stream has been
unreachable for ``rig_watchdog_s``, it drains the primary's journal FILE
(the shard's durable truth — ``absorb_journal_file``), promotes itself
(minting the next fencing epoch), and re-seeds its broker from
``unfinished_tasks()`` exactly as a restarted platform does. Store
clients re-home onto it via the replica-rotation contract they already
implement.
"""

from __future__ import annotations

import asyncio
import json
import logging
import time

from aiohttp import web

from ..broker.queue import InMemoryBroker, Message
from ..metrics import MetricsRegistry
from ..taskstore import TaskNotFound, TaskStatus
from ..taskstore.http import make_app
from ..taskstore.journal import JournalCorruptError
from ..taskstore.sharding import (ShardReplicaLink, absorb_journal_file,
                                  stable_hash)
from ..taskstore.store import FollowerTaskStore
from .topology import Topology
from .wire import BROKER_DONE_PATH, BROKER_POP_PATH, FEED_PATH, SLOTS_PATH

log = logging.getLogger("ai4e_tpu.rig.storenode")

MOVE_SLOT_PATH = "/v1/rig/move_slot"
IMPORT_PATH = "/v1/rig/import"
LEDGERS_PATH = "/v1/rig/ledgers"


class SlotFence:
    """This node's view of slot ownership — the write fence and the
    ``/v1/rig/slots`` body. ``owned`` starts from the topology's static
    assignment; a live move flips entries and records them in ``fenced``
    (owner None = the copy window) for ring clients to re-fetch."""

    def __init__(self, topo: Topology, shard: int):
        self.shard = shard
        self.slots = topo.slots
        self.owned = {s for s in range(topo.slots)
                      if s % topo.shards == shard}
        self.fenced: dict[int, int | None] = {}

    def slot_for(self, task_id: str) -> int:
        return stable_hash(task_id) % self.slots

    def owns(self, task_id: str) -> bool:
        return self.slot_for(task_id) in self.owned

    def set_owner(self, slot: int, owner: int | None) -> None:
        if owner == self.shard:
            self.owned.add(slot)
        else:
            self.owned.discard(slot)
        self.fenced[slot] = owner

    def to_dict(self) -> dict:
        return {"shard": self.shard,
                "owned": sorted(self.owned),
                "fenced": {str(s): o for s, o in self.fenced.items()}}


class _FeedStream:
    """Terminal-transition fan-out to wire subscribers. The store listener
    may fire from any thread (absorb runs in an executor); events cross
    to each subscriber's queue via ``call_soon_threadsafe``."""

    def __init__(self):
        self._subs: set[asyncio.Queue] = set()
        self._loop: asyncio.AbstractEventLoop | None = None

    def bind_loop(self, loop: asyncio.AbstractEventLoop) -> None:
        self._loop = loop

    def on_task(self, task) -> None:
        if task.canonical_status not in TaskStatus.TERMINAL:
            return
        loop = self._loop
        if loop is None or not self._subs:
            return
        line = (json.dumps(task.to_dict()) + "\n").encode("utf-8")

        def fan_out() -> None:
            for q in list(self._subs):
                q.put_nowait(line)

        try:
            running = asyncio.get_running_loop()
        except RuntimeError:
            running = None
        if loop is running:
            fan_out()
        else:
            try:
                loop.call_soon_threadsafe(fan_out)
            except RuntimeError:
                pass  # loop closed mid-teardown — subscribers are gone too

    async def serve(self, request: web.Request) -> web.StreamResponse:
        resp = web.StreamResponse(
            headers={"Content-Type": "application/x-ndjson"})
        await resp.prepare(request)
        q: asyncio.Queue = asyncio.Queue()
        self._subs.add(q)
        try:
            while True:
                try:
                    line = await asyncio.wait_for(q.get(), 5.0)
                except asyncio.TimeoutError:
                    line = b"{}\n"  # heartbeat keeps the tail's read alive
                await resp.write(line)
        except ConnectionResetError:
            return resp  # tail went away (gateway kill/rotation) — normal
        except asyncio.CancelledError:
            raise
        finally:
            self._subs.discard(q)


class _PrimaryGatedStore:
    """The store as the observability hub sees it: listener callbacks
    fire only while this node is the shard's PRIMARY. A replica's store
    fires the same listeners while ABSORBING the primary's stream — and
    the primary already counted those transitions in ITS registry, so an
    ungated hub would double-count every terminal outcome fleet-wide
    once per replica (the conservation cross-check's exact failure
    mode). The tail between the dead primary's last scrape and a
    promotion is honestly LOST from the fleet counters — documented in
    docs/deployment.md; the journal-based verdict stays authoritative.
    Everything except ``add_listener`` passes through untouched."""

    def __init__(self, store):
        self._store = store

    def add_listener(self, callback) -> None:
        def gated(task) -> None:
            if self._store.role == "primary":
                callback(task)

        self._store.add_listener(gated)

    def __getattr__(self, name):
        return getattr(self._store, name)


class StoreNode:
    def __init__(self, topo: Topology, shard: int, index: int):
        """``index`` -1 = the shard's primary; >= 0 = replica ``index``."""
        self.topo = topo
        self.shard = shard
        self.index = index
        self.is_replica = index >= 0
        self.metrics = MetricsRegistry()
        self.fence = SlotFence(topo, shard)
        path = (topo.replica_journal_path(shard, index) if self.is_replica
                else topo.journal_path(shard))
        # compact_every is huge ON PURPOSE: the journal is the run's full
        # transition history — the verdict's duplicate-terminal scan and
        # epoch-monotonicity check read it after the run, and a compaction
        # rewrite (one record per task) would erase exactly the evidence
        # the rig exists to record (docs/deployment.md).
        self.store = FollowerTaskStore(
            path, start_as_primary=not self.is_replica,
            compact_every=int(self.topo.extra.get("compact_every",
                                                  50_000_000)),
            metrics=self.metrics)
        self.store.set_write_fence(self.fence.owns)
        fair = None
        if topo.tenants:
            # Weighted-fair lanes (tenancy/lanes.py) on THIS shard's
            # queue: the upsert's Tenant field rode the wire with the
            # record, so the publisher stamps each message's lane and the
            # DRR dequeue holds the weight ratio inside the shard —
            # exactly where the backlog lives in the rig.
            from ..tenancy import Tenancy
            fair = Tenancy.from_spec(topo.tenants).lanes
        self.broker = InMemoryBroker(
            max_delivery_count=int(topo.extra.get("max_delivery_count", 20)),
            lease_seconds=topo.lease_seconds, metrics=self.metrics,
            fair=fair)
        self.broker.register_queue(self._route_path())
        self.broker.set_dead_letter_handler(self._dead_letter)
        self.store.set_publisher(self.broker.publish)
        self.feed = _FeedStream()
        self.store.add_listener(self.feed.on_task)
        self.flight = None
        self.observability = None
        if topo.observability:
            # The record-owning half of the observability plane: the
            # hub's store listener stamps `completed` onto each
            # timeline, observes created→terminal e2e latency, counts
            # ai4e_request_outcomes_total (the conservation check's
            # terminal side), and keeps this shard's flight-recorder
            # ring — all primary-gated so replica absorption never
            # double-counts (see _PrimaryGatedStore).
            from ..observability.flight import FlightRecorder
            from ..observability.hub import RequestObservability
            self.flight = FlightRecorder(capacity=256,
                                         metrics=self.metrics)
            self.observability = RequestObservability(
                _PrimaryGatedStore(self.store), metrics=self.metrics,
                flight=self.flight)
        self.link: ShardReplicaLink | None = None
        if self.is_replica:
            self.link = ShardReplicaLink(
                None, self.store,
                primary_url=topo.shard_urls(shard)[0],
                wire_timeout=5.0)
        self._watchdog_task: asyncio.Task | None = None
        self._leased: dict[tuple[str, int], Message] = {}
        self._m_promotions = self.metrics.counter(
            "ai4e_rig_promotions_total",
            "Replica self-promotions after a primary watchdog trip")
        self._m_moves = self.metrics.counter(
            "ai4e_rig_slot_moves_total",
            "Live slot moves this node participated in, by side")

    def _route_path(self) -> str:
        from ..taskstore import endpoint_path
        return endpoint_path(self.topo.route)

    def _dead_letter(self, msg: Message) -> None:
        # Conditional: a dead-letter racing a late completion must not
        # clobber the terminal status the client may already have read
        # (AIL003 — the same guard every dispatcher path applies).
        self.store.update_status_if(msg.task_id, TaskStatus.CREATED,
                                    TaskStatus.DEAD_LETTER,
                                    TaskStatus.FAILED)

    # -- rig HTTP surface ---------------------------------------------------

    def build_app(self) -> web.Application:
        app = web.Application(client_max_size=64 * 1024 * 1024)
        make_app(self.store, app=app)
        app.router.add_get(FEED_PATH, self.feed.serve)
        app.router.add_get(SLOTS_PATH, self._get_slots)
        app.router.add_post(SLOTS_PATH, self._set_slot)
        app.router.add_post(BROKER_POP_PATH, self._broker_pop)
        app.router.add_post(BROKER_DONE_PATH, self._broker_done)
        app.router.add_post(MOVE_SLOT_PATH, self._move_slot)
        app.router.add_post(IMPORT_PATH, self._import_records)
        app.router.add_get(LEDGERS_PATH, self._dump_ledgers)
        app.router.add_get("/v1/debug/flight", self._flight_dump)
        app.router.add_get("/healthz", self._health)
        app.router.add_get("/metrics", self._metrics)
        from .nodevitals import attach_vitals
        attach_vitals(app, self.topo, self.metrics)
        app.on_startup.append(self._on_startup)
        app.on_cleanup.append(self._on_cleanup)
        return app

    async def _on_startup(self, _app) -> None:
        loop = asyncio.get_running_loop()
        self.broker.bind_loop(loop)
        self.feed.bind_loop(loop)
        if self.is_replica:
            self._watchdog_task = loop.create_task(self._tail_and_watch())

    async def _on_cleanup(self, _app) -> None:
        if self._watchdog_task is not None:
            self._watchdog_task.cancel()
            try:
                await self._watchdog_task
            except asyncio.CancelledError:
                pass
        self.store.close()

    async def _health(self, _: web.Request) -> web.Response:
        return web.json_response(
            {"status": "healthy", "shard": self.shard,
             "role": self.store.role, "epoch": self.store.epoch})

    async def _metrics(self, _: web.Request) -> web.Response:
        return web.Response(text=self.metrics.render_prometheus(),
                            content_type="text/plain")

    async def _get_slots(self, _: web.Request) -> web.Response:
        return web.json_response(self.fence.to_dict())

    async def _dump_ledgers(self, request: web.Request) -> web.Response:
        """Every resident hop-ledger timeline (bounded) — the driver's
        pre-teardown sweep for the Perfetto timeline export: the ledgers
        are memory-only and die with this process."""
        try:
            limit = int(request.query.get("limit", "5000"))
        except ValueError:
            return web.json_response({"error": "bad limit"}, status=400)
        ledgers = self.store.dump_ledgers(limit=limit)
        return web.json_response({"Shard": self.shard,
                                  "Ledgers": ledgers,
                                  "count": len(ledgers)})

    async def _flight_dump(self, _: web.Request) -> web.Response:
        if self.flight is None:
            return web.json_response(
                {"error": "observability off"}, status=404)
        return web.json_response(self.flight.dump())

    async def _set_slot(self, request: web.Request) -> web.Response:
        """Fence propagation: the move driver (or the source node) flips a
        sibling's table after a live move, so a replica promoted LATER
        owns the moved keyspace correctly."""
        try:
            payload = json.loads(await request.read() or b"{}")
            slot = int(payload["slot"])
            owner = payload["owner"]
            owner = None if owner is None else int(owner)
        except (json.JSONDecodeError, KeyError, TypeError, ValueError):
            return web.json_response({"error": "slot and owner required"},
                                     status=400)
        # Under the store lock: a mutation mid-flight has either passed the
        # fence (and lands before the flip) or re-checks after it — no
        # half-fenced write (the in-process move_slot holds the same lock
        # around its ring flip for the same reason).
        with self.store._lock:
            self.fence.set_owner(slot, owner)
        return web.json_response({"ok": True})

    # -- wire broker --------------------------------------------------------

    async def _broker_pop(self, request: web.Request) -> web.Response:
        try:
            payload = json.loads(await request.read() or b"{}")
            queue = payload.get("queue") or self._route_path()
            wait = min(float(payload.get("wait", 0.0)), 30.0)
        except (json.JSONDecodeError, TypeError, ValueError):
            return web.json_response({"error": "bad pop body"}, status=400)
        if self.store.role != "primary":
            return web.Response(status=204)  # nothing to lease on a follower
        msg = await self.broker.receive(queue, timeout=wait or 0.05)
        if msg is None:
            return web.Response(status=204)
        self._leased[(queue, msg.seq)] = msg
        return web.json_response({
            "TaskId": msg.task_id, "Endpoint": msg.endpoint,
            "BodyHex": msg.body.hex(), "ContentType": msg.content_type,
            "EnqueuedAt": msg.enqueued_at,
            "DeliveryCount": msg.delivery_count, "Seq": msg.seq,
            "LeaseExpires": msg.lease_expires, "Queue": msg.queue_name,
            "CacheKey": msg.cache_key, "DeadlineAt": msg.deadline_at,
            "Priority": msg.priority, "Tenant": msg.tenant})

    async def _broker_done(self, request: web.Request) -> web.Response:
        try:
            payload = json.loads(await request.read() or b"{}")
            queue = payload.get("queue") or self._route_path()
            seq = int(payload["seq"])
            outcome = payload.get("outcome", "complete")
        except (json.JSONDecodeError, KeyError, TypeError, ValueError):
            return web.json_response({"error": "bad done body"}, status=400)
        msg = self._leased.pop((queue, seq), None)
        if msg is None:
            # Lease state died with a previous primary, or the reaper
            # already redelivered: the no-op IS the contract — duplicate
            # suppression absorbs the redelivery.
            return web.json_response({"ok": False, "reason": "unknown seq"})
        if outcome == "abandon":
            self.broker.abandon(msg)
        else:
            self.broker.complete(msg)
        return web.json_response({"ok": True})

    # -- live rebalance (wire move_slot) ------------------------------------

    async def _move_slot(self, request: web.Request) -> web.Response:
        """Move one slot's keyspace to another shard, cross-process.
        Sequence: fence (writes 409 for the window), export, import on
        the destination (rotating across its node URLs — its primary may
        be a promoted replica), flip + forget, propagate the flip to
        every sibling node. Failure before the flip rolls the fence back."""
        try:
            payload = json.loads(await request.read() or b"{}")
            slot = int(payload["slot"])
            dest = int(payload["dest"])
        except (json.JSONDecodeError, KeyError, TypeError, ValueError):
            return web.json_response({"error": "slot and dest required"},
                                     status=400)
        if self.store.role != "primary":
            return web.json_response({"error": "not primary"}, status=503,  # ai4e: noqa[AIL015] — X-Not-Primary is a rotate marker: the wire client tries the next node NOW, waiting would be wrong
                                     headers={"X-Not-Primary": "1"})
        if slot not in self.fence.owned:
            return web.json_response(
                {"error": f"slot {slot} is not owned here"}, status=409)
        if not 0 <= dest < self.topo.shards or dest == self.shard:
            return web.json_response({"error": "bad dest"}, status=400)
        with self.store._lock:
            self.fence.set_owner(slot, None)  # copy window: writes 409
        try:
            ids = [tid for tid in list(self.store._tasks)
                   if self.fence.slot_for(tid) == slot]
            recs = self.store.export_task_records(ids)
            imported = await self._post_import(dest, slot, recs)
        except Exception as exc:  # noqa: BLE001 — roll the fence back; the slot must not stay ownerless
            with self.store._lock:
                self.fence.set_owner(slot, self.shard)
            log.exception("move of slot %d to shard %d failed; fence "
                          "rolled back", slot, dest)
            return web.json_response({"error": f"import failed: {exc}"},
                                     status=502)
        with self.store._lock:
            self.fence.set_owner(slot, dest)
        self.store.forget_tasks(ids)
        self._m_moves.inc(side="source")
        await self._propagate_fence(slot, dest)
        log.info("moved slot %d -> shard %d (%d tasks, %d records)",
                 slot, dest, len(ids), imported)
        return web.json_response({"ok": True, "moved": len(ids),
                                  "records": imported})

    async def _post_import(self, dest: int, slot: int,
                           recs: list[dict]) -> int:
        import aiohttp
        body = json.dumps({"slot": slot, "records": recs})
        last: Exception | None = None
        async with aiohttp.ClientSession() as session:
            for base in self.topo.shard_urls(dest):
                try:
                    async with session.post(
                            base + IMPORT_PATH, data=body,
                            timeout=aiohttp.ClientTimeout(total=30)) as resp:
                        if resp.status == 503:
                            continue  # follower — try the next node
                        payload = await resp.json()
                        if resp.status != 200:
                            raise RuntimeError(
                                f"import answered {resp.status}: {payload}")
                        return int(payload.get("applied", 0))
                except (aiohttp.ClientError, asyncio.TimeoutError,
                        OSError) as exc:
                    last = exc
                    continue
        raise RuntimeError(f"no destination node accepted the import "
                           f"({last!r})")

    async def _import_records(self, request: web.Request) -> web.Response:
        try:
            payload = json.loads(await request.read() or b"{}")
            slot = int(payload["slot"])
            recs = payload.get("records", [])
        except (json.JSONDecodeError, KeyError, TypeError, ValueError):
            return web.json_response({"error": "bad import body"},
                                     status=400)
        if self.store.role != "primary":
            return web.json_response({"error": "not primary"}, status=503,  # ai4e: noqa[AIL015] — X-Not-Primary is a rotate marker: the wire client tries the next node NOW, waiting would be wrong
                                     headers={"X-Not-Primary": "1"})
        applied = self.store.import_task_records(recs)
        with self.store._lock:
            self.fence.set_owner(slot, self.shard)
        # Transport responsibility moves WITH the keyspace: in-process the
        # old shard's sub-queue outlives the move, but here the source's
        # broker dies with its process — an imported non-terminal task
        # whose only message lived there would be stranded. Republish on
        # OUR broker; if the source's message still drains too, that is
        # one duplicate delivery and duplicate suppression's job.
        republished = 0
        for rec in recs:
            tid = rec.get("TaskId", "")
            if not tid or rec.get("Result") or rec.get("Evict"):
                continue
            try:
                task = self.store.get(tid)
            except TaskNotFound:
                continue
            if task.canonical_status not in TaskStatus.TERMINAL:
                self.broker.publish(task)
                republished += 1
        self._m_moves.inc(side="dest")
        return web.json_response({"ok": True, "applied": applied,
                                  "republished": republished})

    async def _propagate_fence(self, slot: int, owner: int) -> None:
        """Best-effort fence flip on every sibling node of both shards —
        a replica promoted after this move must own the right range. A
        node that is down simply misses it (it also missed the records;
        the residual is documented in docs/deployment.md)."""
        import aiohttp
        port = (self.topo.replica_port(self.shard, self.index)
                if self.is_replica else self.topo.shard_port(self.shard))
        my_url = f"http://{self.topo.host}:{port}"
        targets = []
        for s in {self.shard, owner}:
            targets.extend(self.topo.shard_urls(s))
        body = json.dumps({"slot": slot, "owner": owner})
        async with aiohttp.ClientSession() as session:
            for base in targets:
                if base == my_url:
                    continue
                try:
                    async with session.post(
                            base + SLOTS_PATH, data=body,
                            timeout=aiohttp.ClientTimeout(total=5)):
                        pass
                except (aiohttp.ClientError, asyncio.TimeoutError,
                        OSError) as exc:  # best-effort propagation; a dead sibling missed the records too and the residual is documented
                    log.debug("fence propagation to %s failed: %s",
                              base, exc)

    # -- replica tail + watchdog self-promotion -----------------------------

    async def _tail_and_watch(self) -> None:
        """Wire journal tail with a down-detector: ``rig_watchdog_s`` of
        consecutive unreachable polls → the primary is presumed dead →
        drain its journal FILE and promote."""
        # Staggered succession: replica r waits one extra watchdog period
        # per index, and probes its elders before promoting — so N
        # replicas of one shard cannot double-promote into a split brain
        # (the in-process ``_fail_over`` gets this for free by popping one
        # link under a lock; across processes the stagger + probe is the
        # ordering).
        watchdog_s = (float(self.topo.extra.get("watchdog_s", 2.0))
                      * (self.index + 1))
        interval = float(self.topo.extra.get("tail_interval", 0.2))
        down_since: float | None = None
        while True:
            try:
                await asyncio.to_thread(self.link.sync_once)
                down_since = None
            except asyncio.CancelledError:
                raise
            except OSError as exc:
                now = time.monotonic()
                if down_since is None:
                    down_since = now
                    log.warning("shard %d replica %d: primary stream "
                                "unreachable (%s); watchdog armed",
                                self.shard, self.index, exc)
                elif now - down_since >= watchdog_s:
                    if await self._primary_alive():
                        # Starved, not dead: the r13 observability plane
                        # caught the rig's primaries at 1.7 s+ event-loop
                        # lag under saturation — enough for the stream
                        # tail to time out past watchdog_s while the
                        # primary still serves. Promoting then is a
                        # SPLIT BRAIN (two writers, mass task loss — a
                        # red r13 take recorded exactly that). A
                        # SIGKILLed primary refuses the probe instantly,
                        # so real failover pays ~one RTT; a wedged-but-
                        # listening one delays failover by at most the
                        # probe timeout per watchdog period
                        # (docs/deployment.md residual).
                        log.warning(
                            "shard %d replica %d: primary stream dark "
                            "%.1fs but /healthz still answers — starved,"
                            " not dead; watchdog re-armed",
                            self.shard, self.index, now - down_since)
                        down_since = None
                    else:
                        await self._promote()
                        return
            except RuntimeError:
                return  # promoted out from under the tail (absorb refused)
            except Exception:  # noqa: BLE001 — keep tailing through transient absorb errors
                log.exception("shard %d replica %d: tail failed; retrying",
                              self.shard, self.index)
            await asyncio.sleep(interval)

    async def _primary_alive(self) -> bool:
        """Last-chance liveness probe before self-promotion: does the
        primary still answer ``/healthz`` as a primary, given a generous
        timeout? Distinguishes dead (connection refused — promote now)
        from starved (late 200 — re-arm)."""
        import aiohttp
        timeout = float(self.topo.extra.get("promote_probe_timeout_s",
                                            10.0))
        try:
            async with aiohttp.ClientSession() as session:
                async with session.get(
                        self.link.primary_url + "/healthz",
                        timeout=aiohttp.ClientTimeout(
                            total=timeout)) as resp:
                    if resp.status != 200:
                        return False
                    payload = await resp.json()
        except (aiohttp.ClientError, asyncio.TimeoutError, OSError,
                ValueError):
            return False
        # A deposed/demoted holdover answering as a follower is not a
        # live primary — promotion should proceed.
        return payload.get("role") == "primary"

    async def _promote(self) -> None:
        """The failover: drain the dead primary's journal file (durable
        truth — every acknowledged write is in it), promote (minting the
        next fencing epoch), re-seed the broker from unfinished tasks —
        the exact sequence the in-process ``_fail_over`` runs, with the
        file drain standing in for the unreachable stream."""
        elder = await self._find_promoted_elder()
        if elder is not None:
            # An earlier replica already promoted: re-home the tail onto
            # it instead of minting a competing epoch.
            log.warning("shard %d replica %d: elder replica at %s already "
                        "primary; re-homing the tail", self.shard,
                        self.index, elder)
            self.link.primary_url = elder
            self.link.generation = -1  # full resync from the new lineage
            loop = asyncio.get_running_loop()
            self._watchdog_task = loop.create_task(self._tail_and_watch())
            return
        primary_journal = self.topo.journal_path(self.shard)
        try:
            lines = await asyncio.to_thread(
                absorb_journal_file, self.store, primary_journal)
        except JournalCorruptError as exc:
            # Park contract: the verified prefix is applied; promote on it
            # rather than leaving the shard writer-less.
            log.error("shard %d replica %d: journal drain hit a corrupt "
                      "record (%s); promoting on the verified prefix",
                      self.shard, self.index, exc)
            lines = -1
        self.store.promote()
        self._m_promotions.inc()
        reseeded = 0
        for task in self.store.unfinished_tasks():
            self.broker.publish(task)
            reseeded += 1
        log.warning(
            "shard %d replica %d PROMOTED at epoch %d (drained %s journal "
            "lines, re-seeded %d unfinished tasks)", self.shard, self.index,
            self.store.epoch, lines, reseeded)

    async def _find_promoted_elder(self) -> str | None:
        """URL of a lower-index replica that already answered
        ``role: primary``, else None. Unreachable elders are skipped —
        they may be dead too; the stagger gives a live one time to claim
        the role first."""
        if self.index == 0:
            return None
        import aiohttp
        async with aiohttp.ClientSession() as session:
            for r in range(self.index):
                base = self.topo.shard_urls(self.shard)[1 + r]
                try:
                    async with session.get(
                            base + "/v1/taskstore/role",
                            timeout=aiohttp.ClientTimeout(total=2)) as resp:
                        if resp.status != 200:
                            continue
                        if (await resp.json()).get("role") == "primary":
                            return base
                except (aiohttp.ClientError, asyncio.TimeoutError,
                        OSError):  # a dead elder is exactly the case the probe exists to rule out; fall through to the next candidate
                    continue
        return None


async def run_storenode(topo: Topology, shard: int, index: int) -> None:
    from .supervisor import serve_until_signal
    node = StoreNode(topo, shard, index)
    port = (topo.replica_port(shard, index) if index >= 0
            else topo.shard_port(shard))
    await serve_until_signal(node.build_app(), topo.host, port)
