"""The rig's rolling-upgrade driver — the rollout controller against
REAL OS processes (docs/deployment.md#rollouts).

``RigFleet`` is the controller's fleet adapter over the live topology:

- ``drain``    — POST the worker's drain verb (``workernode.DRAIN_PATH``);
  the worker flips to 503 + ``X-Draining`` and the dispatcher both
  redelivers the refused tasks to peers AND ejects the replica from
  placement (``resilience/health.mark_draining``) — no breaker trip;
- ``upgrade``  — SIGKILL + respawn through the supervisor with a bumped
  ``AI4E_ROLLOUT_GENERATION`` (``Supervisor.respawn`` env overrides
  stick, so a crash-loop restart keeps the new generation);
- ``set_split``— POST every dispatcher's ``/v1/rollout/weights`` with the
  url→generation map + the canary share (``rollout/canary.py`` rescales
  the weighted pick);
- ``burn``     — scrape every worker's ``ai4e_rollout_outcomes_total``
  and turn the canary generation's error ratio into fast (last two
  samples) and slow (since rollout start) burn rates against the
  configured error budget — the multi-window shape the production SLO
  engine exports (``observability/slo.py``);
- ``breaker_open`` — scrape the dispatchers' breaker-state gauge for any
  open breaker on a canary-generation backend;
- ``stamp``    — rollout/rollback hop-ledger evidence appended to a
  marker task the driver admitted THROUGH the gateway (so the fleet's
  conservation cross-check stays balanced); the pre-teardown ledger
  sweep carries it into ``ledgers.json``/``timeline.json``.

Scenarios (``topo.rollout``): ``clean`` upgrades every worker and must
promote; ``bad-canary`` seeds ``topo.rollout_error_rate`` of 500s into
generations >= ``rollout_bad_generation`` and must auto-rollback before
the canary's share passes 50%.
"""

from __future__ import annotations

import asyncio
import json
import logging
import time
import urllib.request

from ..observability.federation import parse_prometheus
from ..observability.ledger import ROLLBACK, ROLLOUT, ledger_event
from ..rollout.controller import RolloutController, RolloutPolicy
from .supervisor import Supervisor
from .topology import Topology
from .wire import RingStoreClient
from .workernode import DRAIN_PATH, GENERATION_ENV

log = logging.getLogger("ai4e_tpu.rig.rollout")

#: Error budget the burn windows divide by — 5% canary error ratio is a
#: burn of 1.0 (override via ``topo.extra["rollout_error_budget"]``).
DEFAULT_ERROR_BUDGET = 0.05


def _http_json(url: str, body: dict | None = None,
               timeout: float = 10.0) -> dict | None:
    """Blocking JSON request (run via ``asyncio.to_thread``); None on any
    transport failure — every rollout verb is retried/recorded, never
    allowed to wedge the driver."""
    try:
        data = None if body is None else json.dumps(body).encode()
        req = urllib.request.Request(
            url, data=data, method="POST" if body is not None else "GET",
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return json.loads(resp.read())
    except (OSError, ValueError):
        return None


def _fetch_text(url: str, timeout: float = 5.0) -> str:
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return resp.read().decode("utf-8", "replace")
    except OSError:
        return ""


class RigFleet:
    """Fleet adapter (``rollout/controller.py`` duck-type) over the live
    rig: worker ids are supervisor child names (``worker{s}.{w}``)."""

    def __init__(self, topo: Topology, sup: Supervisor,
                 ring: RingStoreClient, old_generation: int = 1):
        self.topo = topo
        self.sup = sup
        self.ring = ring
        self.old_generation = old_generation
        self.events: list[dict] = []      # recorded into rollout.json
        self.marker_task_id: str | None = None
        self._generations: dict[str, int] = {}   # child name -> generation
        # (t, ok, err) cumulative samples for the canary generation —
        # fast burn reads the last two, slow burn reads first vs last.
        self._burn_samples: list[tuple[float, float, float]] = []
        self.error_budget = float(
            topo.extra.get("rollout_error_budget", DEFAULT_ERROR_BUDGET))

    # -- addressing ---------------------------------------------------------

    def workers(self) -> list[str]:
        return [f"worker{s}.{w}" for s in range(self.topo.shards)
                for w in range(self.topo.workers)]

    def _ports(self, name: str) -> int:
        shard, index = name.removeprefix("worker").split(".")
        return self.topo.worker_port(int(shard), int(index))

    def _base_url(self, name: str) -> str:
        return f"http://{self.topo.host}:{self._ports(name)}"

    def _backend_url(self, name: str) -> str:
        """The exact backend id the shard's dispatcher weighs
        (``topo.worker_urls`` entry — base + route)."""
        return self._base_url(name) + self.topo.route

    def _dispatcher_urls(self) -> list[str]:
        return [f"http://{self.topo.host}:{self.topo.dispatcher_port(s, d)}"
                for s in range(self.topo.shards)
                for d in range(self.topo.dispatchers)]

    def generation_of(self, name: str) -> int:
        return self._generations.get(name, self.old_generation)

    # -- controller verbs ---------------------------------------------------

    async def _dispatcher_post(self, extra: dict) -> None:
        """POST every dispatcher's rollout verb with the CURRENT
        url→generation map plus ``extra`` — every call refreshes the map,
        so a reverted worker re-enters its generation group immediately
        (a stale map would pin it at the canary's zeroed share)."""
        body = {
            "generations": {self._backend_url(n): self.generation_of(n)
                            for n in self.workers()},
            **extra,
        }
        results = await asyncio.gather(
            *(asyncio.to_thread(_http_json, url + "/v1/rollout/weights",
                                body)
              for url in self._dispatcher_urls()))
        if not any(results):
            log.warning("no dispatcher accepted the rollout verb %s", body)

    async def drain(self, worker: str) -> bool:
        # Eject from placement FIRST (covers drain + kill + respawn —
        # without the mark, deliveries into the restart window become
        # connect errors, the breaker opens, and the guard reads a
        # healthy upgrade as a canary breach), then run the drain verb.
        ttl = self.topo.rollout_drain_timeout_ms / 1000.0 + 60.0
        await self._dispatcher_post(
            {"draining": {self._backend_url(worker): ttl}})
        summary = await asyncio.to_thread(
            _http_json, self._base_url(worker) + DRAIN_PATH,
            {"timeout_ms": self.topo.rollout_drain_timeout_ms},
            max(10.0, self.topo.rollout_drain_timeout_ms / 1000.0 + 5.0))
        return bool(summary and summary.get("clean"))

    async def _restart_at(self, worker: str, generation: int) -> None:
        await asyncio.to_thread(self.sup.kill, worker)
        # SIGKILL is asynchronous: wait for the reap before respawning
        # (the supervisor refuses to respawn a child it still sees
        # alive; the chaos verbs dodge this with their respawn gap).
        child = self.sup.children[worker]
        deadline = time.monotonic() + 10.0
        while child.alive() and time.monotonic() < deadline:
            await asyncio.sleep(0.05)
        await asyncio.to_thread(
            self.sup.respawn, worker,
            {GENERATION_ENV: str(int(generation))})
        self._generations[worker] = int(generation)

    async def upgrade(self, worker: str, generation: int) -> None:
        await self._restart_at(worker, generation)

    async def revert(self, worker: str, generation: int) -> None:
        await self._restart_at(worker, generation)

    async def wait_healthy(self, worker: str) -> bool:
        try:
            await asyncio.to_thread(self.sup.wait_healthy, worker, 30.0)
        except Exception:  # noqa: BLE001 — an unhealthy upgrade is a rollback trigger, not a driver crash
            log.warning("%s not healthy after restart", worker)
            return False
        # Re-admit: clear the drain mark and the breaker history the
        # restart window may have minted — a fresh process earns a
        # clean slate (resilience/health.reset).
        await self._dispatcher_post(
            {"undrain": [self._backend_url(worker)]})
        return True

    async def set_split(self, generation: int, share: float) -> None:
        await self._dispatcher_post({"canary_generation": int(generation),
                                     "share": float(share)})

    async def burn(self, generation: int) -> dict:
        """Canary error ratio → fast/slow burn. ok/error counts come from
        every worker's ``ai4e_rollout_outcomes_total`` for the canary
        generation's label; a dead/unreachable worker contributes
        nothing (its counters are at their last value anyway)."""
        ok = err = 0.0
        pages = await asyncio.gather(
            *(asyncio.to_thread(_fetch_text,
                                self._base_url(n) + "/metrics")
              for n in self.workers()))
        wanted = str(int(generation))
        for page in pages:
            for (name, labels), value in parse_prometheus(page).items():
                if name != "ai4e_rollout_outcomes_total":
                    continue
                if f'generation="{wanted}"' not in labels:
                    continue
                if 'outcome="ok"' in labels:
                    ok += value
                elif 'outcome="error"' in labels:
                    err += value
        self._burn_samples.append((time.monotonic(), ok, err))

        def ratio(d_ok: float, d_err: float) -> float:
            total = d_ok + d_err
            return (d_err / total) if total > 0 else 0.0

        fast = slow = 0.0
        if len(self._burn_samples) >= 2:
            t0, ok0, err0 = self._burn_samples[-2]
            fast = ratio(ok - ok0, err - err0) / self.error_budget
            t0, ok0, err0 = self._burn_samples[0]
            slow = ratio(ok - ok0, err - err0) / self.error_budget
        return {"fast": fast, "slow": slow}

    def breaker_open(self, generation: int) -> bool:
        """Any OPEN breaker (state 2) on a canary-generation backend —
        scraped synchronously from the dispatchers (the guard tick calls
        this once per second; the pages are small)."""
        canary = {f"{self.topo.host}:{self._ports(n)}"
                  for n in self.workers()
                  if self.generation_of(n) == int(generation)}
        for url in self._dispatcher_urls():
            for (name, labels), value in parse_prometheus(
                    _fetch_text(url + "/metrics")).items():
                if name != "ai4e_resilience_breaker_state" or value < 2:
                    continue
                # The gauge's backend label is the URI's netloc
                # (resilience/health._label).
                if any(f'backend="{b}"' in labels for b in canary):
                    return True
        return False

    async def stamp(self, event: str, reason: str) -> None:
        record = {"t": round(time.time(), 2), "event": event,
                  "reason": reason}
        self.events.append(record)
        log.info("rollout: %s — %s", event, reason)
        if self.marker_task_id:
            try:
                await self.ring.append_ledger(
                    self.marker_task_id,
                    [ledger_event(event, "rollout", reason=reason)])
            except Exception:  # noqa: BLE001 — ledger evidence is fail-open telemetry, the rollout.json record above is authoritative
                log.debug("rollout ledger stamp dropped", exc_info=True)


async def _admit_marker_task(topo: Topology) -> str | None:
    """One REAL task through the balancer — its TaskId anchors the
    rollout/rollback ledger evidence on an owning shard, and because it
    was admitted by a gateway (and completes through a worker), the
    fleet conservation cross-check stays balanced."""
    body = await asyncio.to_thread(
        _http_json, topo.balancer_url() + topo.route,
        {"rollout": "marker"}, 30.0)
    if body is None or "TaskId" not in body:
        log.warning("could not admit the rollout marker task")
        return None
    return str(body["TaskId"])


async def run_rollout(topo: Topology, sup: Supervisor,
                      window_opens_at: float) -> dict:
    """Drive one rolling upgrade against the live rig and return the
    record for ``rig.json``/``rollout.json``. Starts a beat after the
    measured window opens so the upgrade happens UNDER load — that is
    the scenario."""
    delay = window_opens_at + 1.0 - time.time()
    if delay > 0:
        await asyncio.sleep(delay)
    ring = RingStoreClient(topo.all_shard_urls(), slots=topo.slots)
    record: dict = {"scenario": topo.rollout, "started_at": time.time()}
    try:
        fleet = RigFleet(topo, sup, ring, old_generation=1)
        fleet.marker_task_id = await _admit_marker_task(topo)
        policy = RolloutPolicy(
            drain_timeout_ms=topo.rollout_drain_timeout_ms,
            canary_steps=topo.rollout_steps,
            step_hold_s=topo.rollout_hold_s,
            guard_tick_s=min(1.0, max(0.2, topo.rollout_hold_s / 5.0)),
            burn_fast_max=1.0, burn_slow_max=1.0)
        controller = RolloutController(fleet, generation=2,
                                       old_generation=1, policy=policy)
        result = await controller.run()
        record.update({
            "outcome": result.outcome,
            "generation": result.generation,
            "reason": result.reason,
            "upgraded": result.upgraded,
            "reverted": result.reverted,
            "weight_history": result.weight_history,
            "marker_task": fleet.marker_task_id,
            "events": fleet.events,
        })
    except Exception as exc:  # noqa: BLE001 — a wedged driver must not abort the run; the missing outcome fails the rollout gate instead
        log.exception("rollout driver failed")
        record["outcome"] = "driver_error"
        record["reason"] = repr(exc)
    finally:
        record["finished_at"] = time.time()
        await ring.aclose()
    return record


def rollout_ok(topo: Topology, record: dict | None) -> tuple[bool, str]:
    """The scenario gate folded into the rig verdict: clean upgrades must
    promote every worker; a bad canary must roll back before its traffic
    share passes 50%."""
    if not topo.rollout:
        return True, "no rollout scenario"
    if not record:
        return False, "rollout scenario configured but no record produced"
    if topo.rollout == "clean":
        if record.get("outcome") != "promoted":
            return False, (f"clean rollout did not promote: "
                           f"{record.get('outcome')} "
                           f"({record.get('reason', '')})")
        missing = [w for w in
                   (f"worker{s}.{w}" for s in range(topo.shards)
                    for w in range(topo.workers))
                   if w not in record.get("upgraded", ())]
        if missing:
            return False, f"clean rollout left workers behind: {missing}"
        return True, "promoted"
    if record.get("outcome") != "rolled_back":
        return False, (f"bad canary was not rolled back: "
                       f"{record.get('outcome')}")
    weights = record.get("weight_history", [])
    if weights and max(weights) > 50.0:
        return False, (f"rollback landed after the canary share passed "
                       f"50% (history {weights})")
    if len(record.get("reverted", ())) < len(record.get("upgraded", ())):
        return False, "rollback did not revert every upgraded worker"
    return True, f"rolled back at {max(weights) if weights else 0:g}%"
