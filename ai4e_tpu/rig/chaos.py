"""The rig's seeded chaos timeline — the existing chaos vocabulary where
a "kill" is a real SIGKILL of a real OS process (docs/deployment.md).

Four verbs, mirroring what PRs 6–10 proved in-process:

- ``kill_gateway``        — SIGKILL one gateway replica; the balancer's
  connect-failover re-homes clients, in-flight long-polls re-poll;
- ``kill_dispatcher``     — SIGKILL one dispatcher process mid-lease,
  respawn it after a gap; the server-side lease expires and redelivers
  (duplicate suppression must absorb the overlap);
- ``move_slot``           — live cross-process rebalance of one hash
  slot under load (``storenode`` wire protocol);
- ``kill_shard_primary``  — SIGKILL one shard's primary store process;
  its wire replica's watchdog drains the journal FILE, promotes at the
  next fencing epoch, and every wire client re-homes by rotation.

The schedule is derived from the topology's seed, so a red run replays
identically (the ``make chaos`` precedent). Offsets are from the moment
the measured window opens (after the loadgens' ramp).
"""

from __future__ import annotations

import asyncio
import json
import logging
import random
import time
import urllib.error
import urllib.request

from .supervisor import Supervisor
from .topology import Topology

log = logging.getLogger("ai4e_tpu.rig.chaos")


def build_timeline(topo: Topology) -> list[dict]:
    """The seeded fault schedule. Spread across the window so each fault's
    recovery is observable before the next lands; the primary kill goes
    last-but-one so the promoted replica serves real traffic for the rest
    of the window (including the post-move keyspace — the fence
    propagation path)."""
    rng = random.Random(topo.seed)
    window = max(8.0, topo.duration)
    gateway = rng.randrange(topo.gateways)
    d_shard = rng.randrange(topo.shards)
    dispatcher = rng.randrange(topo.dispatchers)
    kill_shard = rng.randrange(topo.shards)
    # Move a slot OFF the shard whose primary dies later: the promoted
    # replica must respect a fence flip it only heard about via
    # propagation — the exact cross-process window this rig exists to
    # exercise.
    src_shard = kill_shard
    dest_shard = (src_shard + 1) % topo.shards if topo.shards > 1 else None
    slot = rng.choice([s for s in range(topo.slots)
                       if s % topo.shards == src_shard])
    events = [
        {"at": round(window * 0.15, 1), "verb": "kill_gateway",
         "gateway": gateway},
        {"at": round(window * 0.35, 1), "verb": "kill_dispatcher",
         "shard": d_shard, "dispatcher": dispatcher,
         "respawn_after": 3.0},
    ]
    if dest_shard is not None:
        events.append({"at": round(window * 0.55, 1), "verb": "move_slot",
                       "slot": slot, "src": src_shard, "dest": dest_shard})
    if topo.replicas >= 1:
        events.append({"at": round(window * 0.7, 1),
                       "verb": "kill_shard_primary", "shard": kill_shard})
    return events


async def run_timeline(topo: Topology, sup: Supervisor,
                       events: list[dict], window_opens_at: float) -> None:
    """Execute the schedule against the live rig; stamps each event with
    the wall-clock ``t`` it actually fired at (the goodput curve joins on
    these)."""
    for event in sorted(events, key=lambda e: e["at"]):
        delay = window_opens_at + event["at"] - time.time()
        if delay > 0:
            await asyncio.sleep(delay)
        event["t"] = round(time.time(), 2)
        try:
            await _fire(topo, sup, event)
            event["ok"] = True
        except Exception as exc:  # noqa: BLE001 — a failed injection must not abort the run; it is recorded in the artifact
            log.exception("chaos verb %s failed", event["verb"])
            event["ok"] = False
            event["error"] = repr(exc)


async def _fire(topo: Topology, sup: Supervisor, event: dict) -> None:
    verb = event["verb"]
    if verb == "kill_gateway":
        pid = sup.kill(f"gateway{event['gateway']}")
        log.warning("chaos: SIGKILLed gateway%d (pid %d)",
                    event["gateway"], pid)
    elif verb == "kill_dispatcher":
        name = f"dispatcher{event['shard']}.{event['dispatcher']}"
        pid = sup.kill(name)
        log.warning("chaos: SIGKILLed %s (pid %d); respawning in %.1fs",
                    name, pid, event["respawn_after"])
        await asyncio.sleep(event["respawn_after"])
        sup.respawn(name)
        event["respawned_t"] = round(time.time(), 2)
    elif verb == "move_slot":
        url = (topo.shard_urls(event["src"])[0] + "/v1/rig/move_slot")
        body = json.dumps({"slot": event["slot"],
                           "dest": event["dest"]}).encode()

        def post() -> dict:
            req = urllib.request.Request(
                url, data=body, method="POST",
                headers={"Content-Type": "application/json"})
            try:
                with urllib.request.urlopen(req, timeout=60) as resp:
                    return json.loads(resp.read())
            except urllib.error.HTTPError as exc:
                # A refused move is a chaos OUTCOME, not a driver crash:
                # 409 = the slot is already mid-handoff (fence held by a
                # previous move), 503 = the source node is not primary /
                # draining. Either way the schedule continues.
                exc.read()
                if exc.code in (409, 503):
                    return {"refused": exc.code}
                raise

        result = await asyncio.to_thread(post)
        if "refused" in result:
            event["refused"] = result["refused"]
            log.warning("chaos: move_slot %d shard %d -> %d refused "
                        "(HTTP %s); schedule continues",
                        event["slot"], event["src"], event["dest"],
                        result["refused"])
        else:
            event["moved"] = result.get("moved")
            log.warning("chaos: moved slot %d shard %d -> %d (%s tasks)",
                        event["slot"], event["src"], event["dest"],
                        result.get("moved"))
    elif verb == "kill_shard_primary":
        pid = sup.kill(f"store{event['shard']}")
        log.warning("chaos: SIGKILLed shard %d primary (pid %d); replica "
                    "watchdog owns the failover now", event["shard"], pid)
    else:
        raise ValueError(f"unknown chaos verb {verb!r}")
