"""Process supervision as a robustness surface (ISSUE 11, docs/deployment.md).

``scripts/soak.sh`` learned these lessons by hand and encoded them in
bash: a previous run's control plane can outlive its SIGTERM by minutes
(the signal lands when the event loop breathes), so you must wait for the
ports and then escalate to SIGKILL on whatever still holds them; children
must die with the parent or they leak; a child that dies at boot must
fail the run loudly, not hang it. This module is that knowledge as code,
shared by the rig driver and the (now thin) soak script:

- **port eviction** (``ensure_port_free``): wait for a listener to drain,
  then SIGKILL the holder found via ``/proc/net/tcp`` — no ``ss``/psutil
  dependency;
- **health-gated spawn**: a child is not "up" until its health URL
  answers (or its port accepts), bounded by a deadline; a child that
  EXITS while we wait fails immediately with its log tail;
- **crash-loop detection**: the monitor restarts an unexpectedly-dead
  child at most ``max_restarts`` times, and only counts an uptime under
  ``min_uptime_s`` as a crash-loop strike — a child the chaos timeline
  killed on purpose is marked expected and never restarted;
- **hard teardown** (``shutdown``): SIGTERM the process GROUPS (children
  are spawned with ``start_new_session=True``, so grandchildren die
  too), bounded grace, SIGKILL the stragglers, reap, then verify the
  rig's ports are actually free — registered via ``atexit`` and usable
  as a context manager, so no exit path leaks processes.

Everything here is deliberately synchronous: supervision must keep
working when the event loop it would ride is the thing that wedged.
"""

from __future__ import annotations

import atexit
import glob
import logging
import os
import signal
import socket
import subprocess
import sys
import time

log = logging.getLogger("ai4e_tpu.rig.supervisor")


class RigError(RuntimeError):
    """A supervision failure the run must surface loudly."""


# -- port forensics (the soak.sh port-wait/SIGKILL ladder, in-process) ------


def port_is_free(host: str, port: int) -> bool:
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        try:
            s.bind((host, port))
            return True
        except OSError:
            return False


def _listen_inodes(port: int) -> set[str]:
    """Socket inodes LISTENing on ``port`` (state 0A), from
    /proc/net/tcp{,6} — hex-encoded local_address:port per line."""
    inodes: set[str] = set()
    for path in ("/proc/net/tcp", "/proc/net/tcp6"):
        try:
            with open(path, encoding="ascii") as fh:
                lines = fh.readlines()[1:]
        except OSError:
            continue
        for line in lines:
            parts = line.split()
            if len(parts) < 10 or parts[3] != "0A":
                continue
            try:
                if int(parts[1].rsplit(":", 1)[1], 16) == port:
                    inodes.add(parts[9])
            except (ValueError, IndexError):
                continue
    return inodes


def pids_listening_on(port: int) -> list[int]:
    """PIDs holding a LISTEN socket on ``port`` — inode → /proc/*/fd scan
    (fd readlinks via the shared vitals helper)."""
    from ..observability.vitals import proc_fd_links

    inodes = _listen_inodes(port)
    if not inodes:
        return []
    wanted = {f"socket:[{ino}]" for ino in inodes}
    pids = []
    for fd_dir in glob.glob("/proc/[0-9]*/fd"):
        pid = fd_dir.split("/")[2]
        if any(target in wanted for _fd, target in proc_fd_links(pid)):
            pids.append(int(pid))
    return pids


def ensure_port_free(host: str, port: int, wait_s: float = 10.0,
                     kill: bool = True) -> None:
    """Wait up to ``wait_s`` for ``port`` to drain; then (``kill``)
    SIGKILL whatever still holds it — a previous run's wedged process —
    and wait again. Raises ``RigError`` if the port cannot be freed."""
    deadline = time.monotonic() + wait_s
    while time.monotonic() < deadline:
        if port_is_free(host, port):
            return
        time.sleep(0.25)
    if not kill:
        raise RigError(f"port {port} still held after {wait_s}s")
    holders = pids_listening_on(port)
    for pid in holders:
        if pid == os.getpid():
            raise RigError(f"port {port} is held by THIS process")
        log.warning("port %d still held by pid %d after %.0fs; SIGKILL "
                    "(the soak.sh escalation ladder)", port, pid, wait_s)
        try:
            os.kill(pid, signal.SIGKILL)
        except OSError:
            pass
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        if port_is_free(host, port):
            return
        time.sleep(0.1)
    raise RigError(f"port {port} could not be freed (holders: {holders})")


# -- children ---------------------------------------------------------------


class Child:
    def __init__(self, name: str, argv: list[str], env: dict,
                 log_path: str, port: int | None = None,
                 health_url: str | None = None,
                 drain_url: str | None = None):
        self.name = name
        self.argv = argv
        self.env = env
        self.log_path = log_path
        self.port = port
        self.health_url = health_url
        # Graceful-drain verb (rollout/, docs/deployment.md#drain): set
        # for children that serve one (workers) — teardown POSTs it
        # best-effort before the SIGTERM so in-flight work redelivers
        # instead of dying with the process.
        self.drain_url = drain_url
        self.proc: subprocess.Popen | None = None
        self.started_at = 0.0
        self.restarts = 0
        self.expected_death = False

    @property
    def pid(self) -> int | None:
        return self.proc.pid if self.proc is not None else None

    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    def log_tail(self, n: int = 20) -> str:
        try:
            with open(self.log_path, "rb") as fh:
                fh.seek(0, os.SEEK_END)
                fh.seek(max(0, fh.tell() - 8192))
                return "\n".join(
                    fh.read().decode("utf-8", "replace").splitlines()[-n:])
        except OSError:
            return "<no log>"


class Supervisor:
    """Owns every rig child process from spawn to verified teardown."""

    def __init__(self, host: str = "127.0.0.1",
                 max_restarts: int = 2, min_uptime_s: float = 5.0):
        self.host = host
        self.max_restarts = max_restarts
        self.min_uptime_s = min_uptime_s
        self.children: dict[str, Child] = {}
        self._down = False
        atexit.register(self.shutdown)

    # -- spawn --------------------------------------------------------------

    def spawn(self, name: str, argv: list[str], env: dict | None = None,
              log_path: str | None = None, port: int | None = None,
              health_url: str | None = None,
              drain_url: str | None = None) -> Child:
        if name in self.children and self.children[name].alive():
            raise RigError(f"child {name!r} already running")
        if port is not None:
            # Port-conflict eviction BEFORE the child boots: a stale
            # holder fails the bind seconds later with a far worse error.
            ensure_port_free(self.host, port)
        child = self.children.get(name) or Child(
            name, argv, dict(env or os.environ),
            log_path or f"/tmp/rig-{name}.log", port=port,
            health_url=health_url, drain_url=drain_url)
        child.argv, child.env = argv, dict(env or os.environ)
        self.children[name] = child
        self._start(child)
        return child

    def _start(self, child: Child) -> None:
        log_fh = open(child.log_path, "ab")
        try:
            # start_new_session: the child leads its own process group, so
            # teardown can kill the GROUP (grandchildren included) and an
            # interactive ^C on the driver doesn't pre-empt our ordered
            # shutdown.
            child.proc = subprocess.Popen(
                child.argv, env=child.env, stdout=log_fh, stderr=log_fh,
                start_new_session=True)
        finally:
            log_fh.close()
        child.started_at = time.monotonic()
        child.expected_death = False
        log.info("spawned %s (pid %d): %s", child.name, child.proc.pid,
                 " ".join(child.argv[:6]))

    # -- health gating ------------------------------------------------------

    def wait_healthy(self, name: str, timeout: float = 60.0) -> None:
        """Block until the child's health URL answers 200 (or, with only a
        port, until TCP accepts). A child that EXITS while we wait fails
        the run immediately with its log tail — a silent boot crash must
        not burn the whole timeout."""
        import urllib.error
        import urllib.request

        child = self.children[name]
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if not child.alive():
                raise RigError(
                    f"{name} died at boot (exit "
                    f"{child.proc.returncode}):\n{child.log_tail()}")
            try:
                if child.health_url:
                    with urllib.request.urlopen(child.health_url,
                                                timeout=2.0) as resp:
                        if resp.status == 200:
                            return
                elif child.port is not None:
                    with socket.create_connection(
                            (self.host, child.port), timeout=2.0):
                        return
                else:
                    return  # nothing to gate on
            except (urllib.error.URLError, OSError, ValueError):
                pass
            time.sleep(0.2)
        raise RigError(f"{name} did not become healthy within {timeout}s:"
                       f"\n{child.log_tail()}")

    # -- chaos hooks --------------------------------------------------------

    def expect_death(self, name: str) -> None:
        """Mark a child the chaos timeline is about to kill: the monitor
        must neither restart it nor count it as a crash."""
        self.children[name].expected_death = True

    def kill(self, name: str, sig: int = signal.SIGKILL) -> int:
        """SIGKILL (default) a child's process group — the chaos verbs'
        process-death primitive. Returns the pid killed."""
        child = self.children[name]
        if not child.alive():
            raise RigError(f"cannot kill {name}: not running")
        child.expected_death = True
        pid = child.proc.pid
        try:
            os.killpg(os.getpgid(pid), sig)
        except OSError:
            os.kill(pid, sig)
        return pid

    def respawn(self, name: str, env_overrides: dict | None = None) -> Child:
        """Relaunch a (dead) child with its original argv/env — the chaos
        timeline's dispatcher-restart verb, and what a crash-loop restart
        does one step at a time. ``env_overrides`` merge into the child's
        env (and STICK for later respawns) — the rolling-upgrade driver's
        generation bump (``AI4E_ROLLOUT_GENERATION``)."""
        child = self.children[name]
        if child.alive():
            raise RigError(f"cannot respawn {name}: still running")
        if env_overrides:
            child.env = {**child.env,
                         **{k: str(v) for k, v in env_overrides.items()}}
        if child.port is not None:
            ensure_port_free(self.host, child.port)
        self._start(child)
        return child

    # -- crash-loop monitor -------------------------------------------------

    def check(self) -> list[str]:
        """One monitor pass: restart unexpectedly-dead children (bounded),
        raise on a crash-looping one. Returns names restarted."""
        restarted = []
        for child in list(self.children.values()):
            if child.alive() or child.proc is None:
                continue
            if child.expected_death:
                continue  # the chaos timeline owns this corpse
            uptime = time.monotonic() - child.started_at
            if uptime >= self.min_uptime_s:
                # A long-lived child dying is a crash, not a crash LOOP —
                # it restarts with a fresh strike budget (the documented
                # contract: only short uptimes count as loop strikes).
                child.restarts = 0
            child.restarts += 1
            if child.restarts > self.max_restarts:
                raise RigError(
                    f"{child.name} is crash-looping (attempt "
                    f"{child.restarts}, uptime {uptime:.1f}s, exit "
                    f"{child.proc.returncode}):\n{child.log_tail()}")
            log.warning("%s died unexpectedly (exit %s, uptime %.1fs); "
                        "restarting (%d/%d)", child.name,
                        child.proc.returncode, uptime, child.restarts,
                        self.max_restarts)
            if child.port is not None:
                ensure_port_free(self.host, child.port)
            self._start(child)
            restarted.append(child.name)
        return restarted

    # -- teardown -----------------------------------------------------------

    @staticmethod
    def _teardown_wave(child: Child) -> int:
        """Drain-first teardown ordering (docs/deployment.md#teardown):
        workers go first (their drain verb redelivers in-flight work),
        then dispatchers (they stop popping a queue whose workers are
        gone), then everything else, stores LAST — every earlier wave may
        still be flushing task state into them."""
        if child.name.startswith("worker"):
            return 0
        if child.name.startswith("dispatcher"):
            return 1
        if child.name.startswith("store"):
            return 3
        return 2

    def _post_drain(self, child: Child, timeout_s: float = 2.0) -> None:
        """Best-effort drain POST before a worker's SIGTERM: bounded,
        fail-open — a worker that cannot answer still dies on the signal
        path below; the drain just lets in-flight work redeliver first."""
        import urllib.error
        import urllib.request

        try:
            req = urllib.request.Request(
                child.drain_url, data=b'{"timeout_ms": 1500}',
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=timeout_s) as resp:
                log.info("drained %s before teardown (HTTP %d)",
                         child.name, resp.status)
        except (urllib.error.URLError, OSError, ValueError) as exc:
            log.debug("teardown drain of %s skipped: %s", child.name, exc)

    def shutdown(self, grace_s: float = 5.0) -> None:
        """Hard teardown that cannot leak: drain-first ordered SIGTERM
        waves (workers → dispatchers → the rest → stores), bounded grace,
        SIGKILL stragglers, reap, then verify our ports are free
        (evicting any holder as the last resort). Idempotent — atexit and
        explicit callers can both run it."""
        if self._down:
            return
        self._down = True
        waves: dict[int, list[Child]] = {}
        for child in self.children.values():
            waves.setdefault(self._teardown_wave(child), []).append(child)
        # Per-wave slice of the grace budget; the global grace loop below
        # stays the fallback bound, so total teardown time is unchanged.
        wave_grace = grace_s / max(1, len(waves)) if waves else grace_s
        for _, members in sorted(waves.items()):
            for child in members:
                if child.drain_url and child.alive():
                    self._post_drain(child)
            for child in members:
                if child.alive():
                    try:
                        os.killpg(os.getpgid(child.proc.pid),
                                  signal.SIGTERM)
                    except OSError:
                        pass
            wave_deadline = time.monotonic() + wave_grace
            while time.monotonic() < wave_deadline:
                if not any(c.alive() for c in members):
                    break
                time.sleep(0.05)
        deadline = time.monotonic() + grace_s
        while time.monotonic() < deadline:
            if not any(c.alive() for c in self.children.values()):
                break
            time.sleep(0.1)
        for child in self.children.values():
            if child.alive():
                log.warning("%s survived SIGTERM grace; SIGKILL",
                            child.name)
                try:
                    os.killpg(os.getpgid(child.proc.pid), signal.SIGKILL)
                except OSError:
                    try:
                        child.proc.kill()
                    except OSError:
                        pass
        for child in self.children.values():
            if child.proc is not None:
                try:
                    child.proc.wait(timeout=5.0)
                except (subprocess.TimeoutExpired, OSError):
                    log.error("%s (pid %s) could not be reaped",
                              child.name, child.pid)
        # The proof the teardown contract demands: nothing of ours still
        # listens. Evict-and-verify rather than trust.
        for child in self.children.values():
            if child.port is not None and not port_is_free(self.host,
                                                           child.port):
                try:
                    ensure_port_free(self.host, child.port, wait_s=2.0)
                except RigError:
                    log.error("port %d still held after teardown",
                              child.port)

    def __enter__(self) -> "Supervisor":
        return self

    def __exit__(self, *exc) -> None:
        self.shutdown()


def python_argv(module: str, *args: str) -> list[str]:
    """Child argv running ``python -m <module>`` with this interpreter."""
    return [sys.executable, "-m", module, *args]


async def serve_until_signal(app, host: str, port: int) -> None:
    """Run one rig role's aiohttp app until SIGTERM/SIGINT — the shared
    child-process main loop (every role exits cleanly on the supervisor's
    group SIGTERM so teardown needs no SIGKILL escalation on the happy
    path)."""
    import asyncio

    from aiohttp import web

    # Short shutdown grace: rig nodes hold long-lived streams (feed
    # tails, long-polls) that would otherwise pin cleanup for aiohttp's
    # default 60 s and force the supervisor's SIGKILL escalation.
    runner = web.AppRunner(app, shutdown_timeout=2.0)
    await runner.setup()
    site = web.TCPSite(runner, host, port)
    await site.start()
    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        loop.add_signal_handler(sig, stop.set)
    log.info("serving on %s:%d", host, port)
    try:
        await stop.wait()
    finally:
        await runner.cleanup()
