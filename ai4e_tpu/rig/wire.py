"""Wire clients the rig's processes share (docs/deployment.md).

``RingStoreClient`` is the store the gateway replicas hold where the
single-process assembly holds ``InMemoryTaskStore``/``ShardedTaskStore``
— the same consistent-hash routing (``taskstore.sharding.stable_hash``
over a fixed slot table) with every verb crossing the task-store HTTP
surface instead of a method call. Three behaviors make it survive the
chaos vocabulary:

- **replica rotation** (per shard): URL lists are primary-first; connect
  errors and 503 ``X-Not-Primary`` rotate, which re-homes the client
  onto a promoted replica with no reconfiguration (the
  ``_HttpStoreClient`` contract workers already use);
- **slot-fence re-routing**: a mutation answered 409 ``X-Not-Owner``
  (the live ``move_slot`` window) re-fetches the answering node's fence
  table (``GET /v1/rig/slots``), flips the local ring and retries — the
  wire analogue of ``ShardedTaskStore._route``'s ``NotOwnerError``
  re-route, including the owner-unknown copy window (bounded backoff);
- **outcome-checked reads**: a miss (204) from a store that may have
  just handed the slot away re-checks the fence table before standing,
  the wire form of the facade's read fencing.

``WireChangeFeedTail`` tails each shard node's terminal-event stream
(``GET /v1/rig/feed``, ndjson) into ONE local ``ShardChangeFeed`` the
gateway's long-poll parks on — so a gateway replica that did not admit a
task still wakes with the record, and a task that migrates shards
mid-wait wakes from whichever node's stream carries the event.

``WireBroker`` gives a dispatcher PROCESS the broker surface
``broker.Dispatcher`` consumes — pop (lease) over HTTP, completion/
abandon acknowledged fire-and-forget: a lost ack simply lets the lease
expire on the shard node, whose redelivery the dispatcher's duplicate
suppression already handles; dead-lettering (and its terminal task
write) is server-side, where the delivery budget lives.
"""

from __future__ import annotations

import asyncio
import json
import logging

import aiohttp

from ..broker.queue import Message
from ..service.task_manager import TaskManagerBase, _HttpStoreClient
from ..taskstore import APITask, NotPrimaryError, TaskNotFound, TaskStatus
from ..taskstore.feed import ShardChangeFeed
from ..taskstore.sharding import stable_hash
from ..taskstore.task import new_task_id

log = logging.getLogger("ai4e_tpu.rig.wire")

FEED_PATH = "/v1/rig/feed"
SLOTS_PATH = "/v1/rig/slots"
BROKER_POP_PATH = "/v1/rig/broker/pop"
BROKER_DONE_PATH = "/v1/rig/broker/done"


def _raise_refusal(resp) -> None:
    """Typed refusals the routed transport can still hand back: a plain
    503 is the owning store refusing load (journal-degraded / draining —
    the X-Not-Primary flavor was already rotated inside ``_request``),
    and a 409 carrying X-Not-Owner is a slot fence the ``_routed``
    attempt budget could not resolve. Both map to the standby contract
    (``NotPrimaryError`` → gateway 503 + Retry-After), never a raw 500;
    a bare 409 (conditional-update precondition) passes through."""
    if resp.status == 503:
        after = resp.headers.get("Retry-After")
        raise NotPrimaryError(
            "shard store refused the request"
            + (f" (retry after {after}s)" if after else ""))
    if resp.status == 409 and resp.headers.get("X-Not-Owner"):
        raise NotPrimaryError("slot fence unresolved for routed request")


class RingStoreClient(TaskManagerBase):
    """Ring-routed task-store client over N shard store processes."""

    _ROUTE_ATTEMPTS = 8

    def __init__(self, shard_urls: list[list[str]], slots: int,
                 api_key: str | None = None, feed_recent: int = 4096):
        if not shard_urls:
            raise ValueError("at least one shard URL list is required")
        self.slots = slots
        self._assign = [i % len(shard_urls) for i in range(slots)]
        self._clients = [_HttpStoreClient(urls, api_key=api_key)
                         for urls in shard_urls]
        # One local feed for ALL shards: the long-poll waiter must wake
        # whichever node's stream carries the event — a task that
        # migrated mid-wait publishes on the destination's stream.
        self._feed = ShardChangeFeed(0, recent=feed_recent)
        self._tails: list[asyncio.Task] = []
        self._tail_stop: asyncio.Event | None = None
        # Slots the last fence-table fetch reported owner-less (a live
        # move's copy window): misses inside them are indeterminate and
        # retried rather than stood by (_routed).
        self._ownerless: set[int] = set()

    # -- ring ---------------------------------------------------------------

    def slot_for(self, task_id: str) -> int:
        return stable_hash(task_id) % self.slots

    def shard_for(self, task_id: str) -> int:
        return self._assign[self.slot_for(task_id)]

    async def _refresh_slots(self, shard: int) -> bool:
        """Pull the fence table from ``shard``'s node; returns whether any
        local assignment flipped. Owner-less fences (the copy window) flip
        nothing — the caller backs off and retries."""
        try:
            resp, body = await self._clients[shard]._request(
                "GET", SLOTS_PATH)
            if resp.status != 200:
                return False
            fenced = json.loads(body).get("fenced", {})
        except (aiohttp.ClientError, asyncio.TimeoutError, OSError,
                ValueError) as exc:
            log.debug("slot refresh from shard %d failed: %s", shard, exc)
            return False
        changed = False
        for slot_s, owner in fenced.items():
            try:
                slot = int(slot_s)
            except ValueError:
                continue
            if not 0 <= slot < self.slots:
                continue
            if owner is None:
                self._ownerless.add(slot)
                continue
            self._ownerless.discard(slot)
            if self._assign[slot] != owner:
                self._assign[slot] = int(owner)
                changed = True
        return changed

    async def _routed(self, task_id: str, method: str, path: str,
                      check_miss: bool = False, **kw):
        """One ring-routed store round trip with fence re-routing. With
        ``check_miss``, a miss (204 no-such-task, 404 unknown-task) from
        a store that may have just handed the slot away re-checks the
        fence table once before standing — the wire form of the sharded
        facade's outcome-checked misses: a node that forgot a moved range
        answers "unknown" BEFORE its ownership fence fires, and without
        this re-check a worker completing a moved task against a stale
        ring would take that 404 at face value and strand the task."""
        rechecked = False
        last = None
        for _ in range(self._ROUTE_ATTEMPTS):
            shard = self.shard_for(task_id)
            resp, body = await self._clients[shard]._request(
                method, path, **kw)
            if resp.status == 409 and resp.headers.get("X-Not-Owner"):
                last = resp
                if not await self._refresh_slots(shard):
                    await asyncio.sleep(0.1)  # owner-less copy window
                continue
            if resp.status in (204, 404) and check_miss:
                slot = self.slot_for(task_id)
                if not rechecked:
                    rechecked = True
                    if await self._refresh_slots(shard) \
                            and self.shard_for(task_id) != shard:
                        continue  # the slot moved; the new owner may know it
                if slot in self._ownerless:
                    # Copy window: the range is mid-handoff and a miss is
                    # indeterminate — back off and re-ask until the fence
                    # resolves (bounded by the attempt budget).
                    last = resp
                    await asyncio.sleep(0.1)
                    await self._refresh_slots(shard)
                    continue
            return resp, body
        raise NotPrimaryError(
            f"could not route task {task_id!r}: slot fenced after "
            f"{self._ROUTE_ATTEMPTS} attempts (last {getattr(last, 'status', '?')})")

    # -- gateway-facing verb surface ---------------------------------------

    async def upsert(self, task: APITask) -> APITask:
        if not task.task_id:
            # Mint here: the id IS the routing key (the sharded facade
            # does exactly this before its ring lookup).
            task.task_id = new_task_id()
        payload = task.to_dict()
        payload["Body"] = task.body.decode("utf-8",
                                           errors="surrogateescape")
        payload["PublishToGrid"] = task.publish
        try:
            resp, body = await self._routed(
                task.task_id, "POST", "/v1/taskstore/upsert",
                data=json.dumps(payload))
        except (aiohttp.ClientError, asyncio.TimeoutError, OSError) as exc:
            # The shard is mid-promotion and the rotation patience ran
            # out: surface the standby contract, not a raw 500 — the
            # gateway answers 503 + Retry-After and the client re-POSTs.
            raise NotPrimaryError(str(exc)) from exc
        _raise_refusal(resp)
        if resp.status != 200:
            raise RuntimeError(
                f"upsert failed: HTTP {resp.status} "
                f"{body[:200].decode('utf-8', 'replace')}")
        return APITask.from_dict(json.loads(body))

    async def get(self, task_id: str) -> APITask:
        resp, body = await self._routed(
            task_id, "GET", "/v1/taskstore/task",
            check_miss=True, params={"taskId": task_id})
        if resp.status == 204:
            raise TaskNotFound(task_id)
        if resp.status != 200:
            raise TaskNotFound(task_id)
        return APITask.from_dict(json.loads(body))

    async def set_result(self, task_id: str, result: bytes,
                         content_type: str = "application/json",
                         stage: str | None = None) -> None:
        params = {"taskId": task_id}
        if stage:
            params["stage"] = stage
        resp, body = await self._routed(
            task_id, "POST", "/v1/taskstore/result", params=params,
            check_miss=True,
            data=result, headers={"Content-Type": content_type})
        _raise_refusal(resp)
        if resp.status == 404:
            raise TaskNotFound(task_id)
        if resp.status != 200:
            raise RuntimeError(f"set_result failed: HTTP {resp.status}")

    def set_len(self, endpoint_path: str, status: str) -> int:
        """Sync by contract (the admission pressure check calls it
        inline); the rig runs gateways admission-off, so an empty backlog
        is the correct degraded answer rather than a wire round trip."""
        return 0

    async def get_ledger(self, task_id: str) -> list[dict]:
        """The task's hop-ledger timeline, fetched from the OWNING shard
        node (it lives beside the record in that store's memory). The
        wire form of the sharded facade's empty→None ownership re-check:
        an empty timeline from a node that may have just handed the slot
        away re-checks the fence table once and re-asks the new owner —
        without it, ``trace --task-id`` against the rig answered ``[]``
        for every task (the PR 11 fail-open this closes). Still
        fail-open on transport errors: the ledger is telemetry, and a
        mid-failover read answers empty, never raises."""
        rechecked = False
        while True:
            try:
                resp, body = await self._routed(
                    task_id, "GET", "/v1/taskstore/ledger",
                    params={"taskId": task_id})
            except (aiohttp.ClientError, asyncio.TimeoutError, OSError,
                    NotPrimaryError):
                return []
            if resp.status != 200:
                return []
            try:
                events = json.loads(body).get("Events") or []
            except ValueError:
                return []
            if events or rechecked:
                return events
            rechecked = True
            shard = self.shard_for(task_id)
            if not await self._refresh_slots(shard) \
                    or self.shard_for(task_id) == shard:
                return []

    async def append_ledger(self, task_id: str, events: list[dict]) -> int:
        """Hop-ledger append ring-routed to the owning shard — how the
        rig gateway's admitted/published stamps (and the echo worker's
        execute stamp) land beside the record. Fail-open like every
        ledger path: a stamp that cannot land is dropped, serving is
        untouched."""
        try:
            resp, body = await self._routed(
                task_id, "POST", "/v1/taskstore/ledger", check_miss=True,
                data=json.dumps({"TaskId": task_id, "Events": events}))
            _raise_refusal(resp)
        except (aiohttp.ClientError, asyncio.TimeoutError, OSError,
                NotPrimaryError):
            return 0
        if resp.status != 200:
            return 0
        try:
            return int(json.loads(body).get("appended", 0))
        except (ValueError, TypeError):
            return 0

    def add_listener(self, listener) -> None:
        """No-op: cross-process components ride the wire feed instead."""

    def feed_for(self, task_id: str) -> ShardChangeFeed:
        return self._feed

    # -- TaskManagerBase (dispatcher/worker-facing) -------------------------

    async def get_task_status(self, task_id: str) -> dict | None:
        resp, body = await self._routed(
            task_id, "GET", "/v1/taskstore/task",
            check_miss=True, params={"taskId": task_id})
        if resp.status != 200:
            return None
        return json.loads(body)

    async def _upsert(self, task: APITask) -> dict:
        return (await self.upsert(task)).to_dict()

    async def _update(self, task_id: str, status: str,
                      backend_status: str | None = None) -> dict:
        payload = {"TaskId": task_id, "Status": status,
                   "BackendStatus": backend_status
                   or TaskStatus.canonical(status)}
        resp, body = await self._routed(
            task_id, "POST", "/v1/taskstore/update",
            check_miss=True, data=json.dumps(payload))
        _raise_refusal(resp)
        if resp.status == 204:
            raise KeyError(f"task not found: {task_id}")
        if resp.status != 200:
            raise RuntimeError(f"update failed: HTTP {resp.status}")
        return json.loads(body)

    async def update_task_status_if(self, task_id: str,
                                    expected_status: str, status: str,
                                    backend_status: str | None = None
                                    ) -> dict | None:
        payload = {"TaskId": task_id, "Status": status,
                   "BackendStatus": backend_status
                   or TaskStatus.canonical(status),
                   "ExpectedStatus": expected_status}
        resp, body = await self._routed(
            task_id, "POST", "/v1/taskstore/update",
            check_miss=True, data=json.dumps(payload))
        _raise_refusal(resp)  # fence-409 is NOT the precondition branch
        if resp.status in (409, 204):
            return None
        if resp.status != 200:
            raise RuntimeError(f"conditional update failed: "
                               f"HTTP {resp.status}")
        return json.loads(body)

    # -- wire change-feed tails --------------------------------------------

    async def start_feed_tails(self) -> None:
        """One tail task per shard, rotating across that shard's node URLs
        (a promoted replica serves the stream too — its absorb path fires
        the same listeners)."""
        self._tail_stop = asyncio.Event()
        loop = asyncio.get_running_loop()
        for shard in range(len(self._clients)):
            self._tails.append(loop.create_task(self._tail(shard)))

    async def _tail(self, shard: int) -> None:
        stop = self._tail_stop
        client = self._clients[shard]
        idx = 0
        while not stop.is_set():
            base = client._endpoints[idx % len(client._endpoints)]
            try:
                session = await client._get_session()
                async with session.get(
                        base + FEED_PATH,
                        timeout=aiohttp.ClientTimeout(total=None,
                                                      sock_read=30)) as resp:
                    if resp.status != 200:
                        raise aiohttp.ClientError(
                            f"feed answered {resp.status}")
                    async for raw in resp.content:
                        if stop.is_set():
                            return
                        line = raw.strip()
                        if not line or line == b"{}":
                            continue  # heartbeat
                        try:
                            task = APITask.from_dict(json.loads(line))
                        except (ValueError, KeyError, TypeError):
                            continue
                        self._feed.publish(task)
            except asyncio.CancelledError:
                raise
            except (aiohttp.ClientError, asyncio.TimeoutError,
                    OSError) as exc:
                log.debug("feed tail shard %d via %s dropped: %s",
                          shard, base, exc)
                idx += 1  # rotate: the primary may be dead, a replica up
                try:
                    await asyncio.wait_for(stop.wait(), 0.5)
                    return
                except asyncio.TimeoutError:
                    continue

    async def aclose(self) -> None:
        if self._tail_stop is not None:
            self._tail_stop.set()
        for task in self._tails:
            task.cancel()
        for task in self._tails:
            try:
                await task
            except (asyncio.CancelledError, Exception):  # noqa: BLE001; ai4e: noqa[AIL005] — awaiting our own cancelled tails at teardown
                pass
        self._tails = []
        for client in self._clients:
            await client.close()


class WireBroker:
    """The broker surface a dispatcher PROCESS consumes, over one shard
    node's ``/v1/rig/broker/*`` routes (rotating to the promoted replica
    like every wire client). ``receive`` long-polls a lease; ``complete``/
    ``abandon`` acknowledge fire-and-forget — a lost ack lets the lease
    expire server-side, and the redelivery is exactly the duplicate the
    dispatcher's suppression path exists for. Dead-lettering is entirely
    server-side (the delivery budget and its terminal task write live
    with the queue), so ``abandon`` always reports "requeued" here."""

    def __init__(self, shard_urls: list[str], lease_seconds: float = 5.0,
                 api_key: str | None = None):
        self._client = _HttpStoreClient(shard_urls, api_key=api_key,
                                        failover_cycles=3,
                                        failover_delay=0.5)
        self.lease_seconds = lease_seconds
        # Strong refs to in-flight fire-and-forget acks (the loop holds
        # tasks weakly; AIL004).
        self._acks: set[asyncio.Task] = set()

    async def receive(self, queue_name: str,
                      timeout: float | None = None) -> Message | None:
        try:
            resp, body = await self._client._request(
                "POST", BROKER_POP_PATH,
                data=json.dumps({"queue": queue_name,
                                 "wait": timeout or 0.0}))
        except (aiohttp.ClientError, asyncio.TimeoutError, OSError) as exc:
            # Mid-failover / node down: the dispatcher loop treats None
            # as an idle poll and re-enters — never dies on transport.
            log.debug("broker pop failed: %s", exc)
            await asyncio.sleep(0.5)
            return None
        if resp.status != 200:
            if resp.status not in (204, 503):
                log.warning("broker pop answered HTTP %d", resp.status)
                await asyncio.sleep(0.2)
            return None
        d = json.loads(body)
        return Message(
            task_id=d["TaskId"], endpoint=d["Endpoint"],
            body=bytes.fromhex(d.get("BodyHex", "")),
            content_type=d.get("ContentType", "application/json"),
            enqueued_at=float(d.get("EnqueuedAt", 0.0)),
            delivery_count=int(d.get("DeliveryCount", 1)),
            seq=int(d.get("Seq", 0)),
            lease_expires=float(d.get("LeaseExpires", 0.0)),
            queue_name=d.get("Queue", queue_name),
            cache_key=d.get("CacheKey", ""),
            deadline_at=float(d.get("DeadlineAt", 0.0)),
            priority=int(d.get("Priority", 1)),
            tenant=d.get("Tenant", ""))

    def _ack(self, msg: Message, outcome: str) -> None:
        async def send() -> None:
            try:
                await self._client._request(
                    "POST", BROKER_DONE_PATH,
                    data=json.dumps({"queue": msg.queue_name,
                                     "seq": msg.seq,
                                     "outcome": outcome}))
            except (aiohttp.ClientError, asyncio.TimeoutError,
                    OSError) as exc:
                # Lost ack = lease expiry = a redelivery the duplicate
                # suppression path absorbs; log so an ack blackout is
                # visible when redelivery rates spike (AIL005).
                log.debug("broker %s ack for seq %d lost: %s",
                          outcome, msg.seq, exc)

        task = asyncio.get_running_loop().create_task(send())
        self._acks.add(task)
        task.add_done_callback(self._acks.discard)

    def complete(self, msg: Message) -> None:
        self._ack(msg, "complete")

    def abandon(self, msg: Message) -> bool:
        self._ack(msg, "abandon")
        return True  # dead-letter bookkeeping is server-side

    async def aclose(self) -> None:
        if self._acks:
            await asyncio.gather(*self._acks, return_exceptions=True)
        await self._client.close()
