"""Front balancer PROCESS — the rig's one client-facing address.

The role the reference fills with its managed front door (Istio ingress /
APIM): round-robin every request across the gateway replicas, and retry a
CONNECT-phase failure against the next replica — a killed gateway costs
its in-flight requests (the client's poll loop re-polls through here and
lands on a survivor), never the address. Only connect failures fail over;
a response that began is returned as-is — the balancer must not replay a
request a gateway may have admitted (the same rule the gateway's own sync
proxy applies).
"""

from __future__ import annotations

import asyncio
import itertools
import logging

import aiohttp
from aiohttp import web

from ..metrics import MetricsRegistry
from .topology import Topology

log = logging.getLogger("ai4e_tpu.rig.balancer")

_HOP_HEADERS = ("host", "content-length", "transfer-encoding", "connection")


class Balancer:
    def __init__(self, topo: Topology):
        self.topo = topo
        self.metrics = MetricsRegistry()
        self._rr = itertools.cycle(range(topo.gateways))
        self._requests = self.metrics.counter(
            "ai4e_balancer_requests_total",
            "Balancer requests by upstream gateway and outcome")
        self._session: aiohttp.ClientSession | None = None
        self.app = web.Application(client_max_size=64 * 1024 * 1024)
        self.app.router.add_get("/healthz", self._health)
        self.app.router.add_get("/metrics", self._metrics)
        # Vitals BEFORE the catch-all: aiohttp resolves in registration
        # order, and /v1/debug/vitals must answer here, not proxy.
        from .nodevitals import attach_vitals
        attach_vitals(self.app, topo, self.metrics)
        self.app.router.add_route("*", "/{tail:.*}", self._proxy)
        self.app.on_cleanup.append(self._cleanup)

    async def _health(self, _: web.Request) -> web.Response:
        return web.json_response({"status": "healthy",
                                  "gateways": self.topo.gateways})

    async def _metrics(self, _: web.Request) -> web.Response:
        return web.Response(text=self.metrics.render_prometheus(),
                            content_type="text/plain")

    async def _get_session(self) -> aiohttp.ClientSession:
        if self._session is None or self._session.closed:
            self._session = aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=90),
                connector=aiohttp.TCPConnector(limit=0))
        return self._session

    async def _cleanup(self, _app) -> None:
        if self._session is not None:
            await self._session.close()

    async def _proxy(self, request: web.Request) -> web.Response:
        body = await request.read()
        headers = {k: v for k, v in request.headers.items()
                   if k.lower() not in _HOP_HEADERS}
        session = await self._get_session()
        last: Exception | None = None
        for _ in range(self.topo.gateways):
            g = next(self._rr)
            target = (self.topo.gateway_urls()[g]
                      + request.path_qs)
            try:
                async with session.request(request.method, target,
                                           data=body,
                                           headers=headers) as resp:
                    payload = await resp.read()
                self._requests.inc(gateway=str(g),
                                   outcome=str(resp.status))
                # Forward the gateway's response headers: shed provenance
                # (X-Shed-Reason) and quota drain (Retry-After) are part
                # of the refusal contract clients key off — a front door
                # that strips them breaks the tenant taxonomy. The body
                # arrives decoded, so content-* framing stays ours.
                resp_headers = {
                    k: v for k, v in resp.headers.items()
                    if k.lower() not in _HOP_HEADERS
                    and k.lower() not in ("content-type",
                                          "content-encoding")}
                return web.Response(status=resp.status, body=payload,
                                    content_type=resp.content_type,
                                    headers=resp_headers)
            except aiohttp.ClientConnectorError as exc:
                # Connect-phase failure ONLY: the gateway never saw the
                # request — safe to offer it to the next replica. A reset
                # of an ESTABLISHED connection (ClientOSError/
                # ConnectionResetError — e.g. the chaos SIGKILL landing
                # after the body was sent) must NOT come through here: the
                # gateway may already have admitted the task, and a replay
                # would mint a second one.
                last = exc
                self._requests.inc(gateway=str(g), outcome="unreachable")
                continue
            except (aiohttp.ClientError, ConnectionResetError, OSError,
                    asyncio.TimeoutError) as exc:
                # Mid-response failure: the gateway may have admitted the
                # task — surface 502, never replay.
                self._requests.inc(gateway=str(g), outcome="broken")
                return web.Response(status=502,
                                    text=f"gateway dropped mid-response: "
                                         f"{exc}")
        return web.Response(status=503,
                            text=f"no gateway reachable: {last}",
                            headers={"Retry-After": "1"})


async def run_balancer(topo: Topology) -> None:
    from .supervisor import serve_until_signal
    balancer = Balancer(topo)
    await serve_until_signal(balancer.app, topo.host, topo.balancer_port())
