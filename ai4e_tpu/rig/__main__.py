"""``python -m ai4e_tpu.rig`` — the rig's process entrypoints.

``up`` is the driver (``make rig`` runs it); every other subcommand is a
child role the driver launches with ``--spec <resolved topology.json>``.
Children derive EVERYTHING from the spec file — the ``AI4E_RIG_*`` env
knobs are driver-side only (docs/config.md documents each).
"""

from __future__ import annotations

import argparse
import asyncio
import json
import logging
import os
import sys

from .topology import Topology


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name)
    return default if raw is None or raw == "" else int(raw)


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name)
    return default if raw is None or raw == "" else float(raw)


def _topology_from_args(args) -> Topology:
    return Topology(
        gateways=args.gateways, shards=args.shards,
        replicas=args.replicas, dispatchers=args.dispatchers,
        workers=args.workers, loadgens=args.loadgens,
        rate=args.rate, duration=args.duration, ramp=args.ramp,
        chaos=not args.no_chaos, seed=args.seed,
        collector=not args.no_collector,
        observability=not args.no_observability,
        work_ms=args.work_ms, base_port=args.base_port,
        workdir=args.workdir, max_inflight=args.max_inflight,
        task_timeout=args.task_timeout,
        tenants=args.tenants,
        loadgen_tenants=(json.loads(args.loadgen_tenants)
                         if args.loadgen_tenants else []),
        mesh=args.mesh, mesh_poison_nths=args.mesh_poison_nths,
        mesh_recovery_s=args.mesh_recovery_s,
        rollout=args.rollout, rollout_error_rate=args.rollout_error_rate,
        rollout_steps=args.rollout_steps,
        rollout_hold_s=args.rollout_hold_s,
        rollout_drain_timeout_ms=args.rollout_drain_timeout_ms)


def main(argv=None) -> int:
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s")
    # Per-request INFO noise (access lines, tracer spans) costs real CPU
    # at rig rates and buries the supervision/chaos/failover lines the
    # run is recorded for.
    logging.getLogger("aiohttp.access").setLevel(logging.WARNING)
    logging.getLogger("ai4e_tpu.trace").setLevel(logging.WARNING)
    parser = argparse.ArgumentParser(prog="ai4e_tpu.rig")
    sub = parser.add_subparsers(dest="cmd", required=True)

    up = sub.add_parser("up", help="launch the rig, drive load, replay "
                                   "chaos, record the artifact")
    up.add_argument("--gateways", type=int,
                    default=_env_int("AI4E_RIG_GATEWAYS", 3))
    up.add_argument("--shards", type=int,
                    default=_env_int("AI4E_RIG_SHARDS", 2))
    up.add_argument("--replicas", type=int,
                    default=_env_int("AI4E_RIG_REPLICAS", 1))
    up.add_argument("--dispatchers", type=int,
                    default=_env_int("AI4E_RIG_DISPATCHERS", 2))
    up.add_argument("--workers", type=int,
                    default=_env_int("AI4E_RIG_WORKERS", 1))
    up.add_argument("--loadgens", type=int,
                    default=_env_int("AI4E_RIG_LOADGENS", 2))
    up.add_argument("--rate", type=float,
                    default=_env_float("AI4E_RIG_RATE", 10000.0))
    up.add_argument("--duration", type=float,
                    default=_env_float("AI4E_RIG_DURATION", 30.0))
    up.add_argument("--ramp", type=float,
                    default=_env_float("AI4E_RIG_RAMP", 3.0))
    up.add_argument("--seed", type=int,
                    default=_env_int("AI4E_RIG_SEED", 20260803))
    up.add_argument("--work-ms", type=float, default=0.0)
    up.add_argument("--base-port", type=int,
                    default=_env_int("AI4E_RIG_BASE_PORT", 18800))
    up.add_argument("--workdir",
                    default=os.environ.get("AI4E_RIG_WORKDIR",
                                           "/tmp/ai4e-rig"))
    up.add_argument("--max-inflight", type=int, default=512)
    up.add_argument("--task-timeout", type=float, default=60.0)
    up.add_argument("--no-chaos", action="store_true",
                    help="measure only; skip the fault timeline")
    up.add_argument("--no-collector", action="store_true",
                    help="skip the fleet-telemetry collector role")
    up.add_argument("--no-observability", action="store_true",
                    help="no hop-ledger stamps / flight rings / vitals "
                         "samplers / timeline (the serving fleet "
                         "byte-identical to PR 11)")
    up.add_argument("--tenants",
                    default=os.environ.get("AI4E_RIG_TENANTS", ""),
                    help="tenant registry spec "
                         "('name=key:weight:rps:burst,...') — enables "
                         "per-gateway quota edges + weighted-fair shard "
                         "lanes (docs/tenancy.md); empty = tenancy off")
    up.add_argument("--loadgen-tenants",
                    default=os.environ.get("AI4E_RIG_LOADGEN_TENANTS", ""),
                    help="JSON list pinning loadgen i to a tenant: "
                         '[{"name": ..., "key": ..., "rate": rps}, ...] — '
                         "rate overrides the even rate/loadgens split "
                         "(the noisy-neighbor lever)")
    up.add_argument("--mesh",
                    default=os.environ.get("AI4E_RIG_MESH", ""),
                    help="mesh layout spec ('dp=8', 'dp=2,tp=2') — boots "
                         "every worker as a mesh endpoint with the tier "
                         "label in its route (docs/mesh_serving.md); "
                         "empty = plain echo workers")
    up.add_argument("--mesh-poison-nths",
                    default=os.environ.get("AI4E_RIG_MESH_POISON_NTHS", ""),
                    help="comma-separated 1-based delivery ordinals each "
                         "mesh worker poisons (503 result-invalidated → "
                         "per-task redelivery; consecutive poisons flip "
                         "the endpoint unhealthy)")
    up.add_argument("--mesh-recovery-s", type=float,
                    default=_env_float("AI4E_RIG_MESH_RECOVERY_S", 2.0),
                    help="seconds a flipped-unhealthy mesh worker stays "
                         "dark before its follower-restart probe")
    up.add_argument("--rollout", default=os.environ.get("AI4E_RIG_ROLLOUT",
                                                        ""),
                    choices=["", "clean", "bad-canary"],
                    help="rolling-upgrade scenario under load "
                         "(docs/deployment.md#rollouts): 'clean' must "
                         "promote with zero loss; 'bad-canary' seeds "
                         "errors into generation 2 and must auto-rollback "
                         "before its share passes 50%%")
    up.add_argument("--rollout-error-rate", type=float,
                    default=_env_float("AI4E_RIG_ROLLOUT_ERROR_RATE", 0.0),
                    help="seeded 500 rate at generations >= 2 "
                         "(bad-canary; 0 with --rollout bad-canary "
                         "defaults to 0.25)")
    up.add_argument("--rollout-steps",
                    default=os.environ.get("AI4E_RIG_ROLLOUT_STEPS",
                                           "25,50,100"),
                    help="canary weight ladder in percent, ending at 100")
    up.add_argument("--rollout-hold-s", type=float,
                    default=_env_float("AI4E_RIG_ROLLOUT_HOLD_S", 3.0),
                    help="clean-burn hold per canary step (s)")
    up.add_argument("--rollout-drain-timeout-ms", type=float,
                    default=_env_float("AI4E_RIG_ROLLOUT_DRAIN_TIMEOUT_MS",
                                       5000.0),
                    help="per-worker drain budget before force-retire")
    up.add_argument("--out", default=None,
                    help="artifact directory (rig.json is written here)")

    soak = sub.add_parser(
        "soak", help="the scripts/soak.sh engine: control plane + worker "
                     "under rig supervision, windowed closed-loop load")
    soak.add_argument("--minutes", type=float, default=10.0)
    soak.add_argument("--out", default="/tmp/soak")

    for role in ("storenode", "gatewaynode", "balancer", "dispatchernode",
                 "workernode", "loadgen", "collector"):
        p = sub.add_parser(role)
        p.add_argument("--spec", required=True)
        if role in ("storenode", "dispatchernode", "workernode"):
            p.add_argument("--shard", type=int, required=True)
        if role not in ("balancer", "collector"):
            p.add_argument("--index", type=int,
                           required=role != "storenode",
                           default=-1 if role == "storenode" else None)

    args = parser.parse_args(argv)

    if args.cmd == "up":
        from .run import run_rig, summarize
        topo = _topology_from_args(args)
        result = asyncio.run(run_rig(topo, out_dir=args.out))
        print(summarize(result))
        print(json.dumps({"ok": result["ok"],
                          "verdict": {k: v for k, v in
                                      result["verdict"].items()
                                      if k != "windows"}}))
        return 0 if result["ok"] else 1
    if args.cmd == "soak":
        from .soak import run_soak
        return asyncio.run(run_soak(minutes=args.minutes, out=args.out))

    topo = Topology.load(args.spec)
    if args.cmd == "storenode":
        from .storenode import run_storenode
        asyncio.run(run_storenode(topo, args.shard, args.index))
    elif args.cmd == "gatewaynode":
        from .gatewaynode import run_gatewaynode
        asyncio.run(run_gatewaynode(topo, args.index))
    elif args.cmd == "balancer":
        from .balancer import run_balancer
        asyncio.run(run_balancer(topo))
    elif args.cmd == "collector":
        from .collectornode import run_collectornode
        asyncio.run(run_collectornode(topo))
    elif args.cmd == "dispatchernode":
        from .dispatchernode import run_dispatchernode
        asyncio.run(run_dispatchernode(topo, args.shard, args.index))
    elif args.cmd == "workernode":
        from .workernode import run_workernode
        asyncio.run(run_workernode(topo, args.shard, args.index))
    elif args.cmd == "loadgen":
        from .loadgen import run_loadgen
        asyncio.run(run_loadgen(topo, args.index))
    return 0


if __name__ == "__main__":
    sys.exit(main())
