"""The rig driver — ``python -m ai4e_tpu.rig up`` / ``make rig``.

Launches the topology as real OS processes under the ``Supervisor``,
drives the multi-process loadgen through the balancer, replays the
seeded chaos timeline at rate, and records the whole run — topology,
per-loadgen windows (offered vs achieved + error taxonomy), the chaos
events with their actual fire times, the per-shard + global invariant
verdict, and the merged per-role metrics — as ONE JSON artifact
(``bench_results/r12-*`` acceptance shape: the scale claim is a file,
not a README paragraph).

Boot order is dependency order: stores first (primaries, then replicas,
each health-gated), then workers, dispatchers, gateways, the balancer,
and only then the loadgens. Teardown is the supervisor's hard contract —
every exit path (success, chaos gone wrong, ^C) runs it, and it verifies
the ports actually drained.
"""

from __future__ import annotations

import asyncio
import glob
import json
import logging
import os
import time

from . import chaos as rig_chaos
from . import verdict as rig_verdict
from ..observability.federation import fetch_json as _fetch_json
from .supervisor import Supervisor, python_argv
from .topology import Topology

log = logging.getLogger("ai4e_tpu.rig.run")


def _spawn_topology(topo: Topology, sup: Supervisor) -> None:
    spec = topo.spec_path()

    def spawn(name: str, role: str, port: int | None, *extra: str,
              drain_url: str | None = None) -> None:
        argv = python_argv("ai4e_tpu.rig", role, "--spec", spec, *extra)
        sup.spawn(name, argv, log_path=os.path.join(topo.workdir,
                                                    f"{name}.log"),
                  port=port,
                  health_url=(f"http://{topo.host}:{port}/healthz"
                              if port else None),
                  drain_url=drain_url)

    # Stores before everything (dependency order); primaries before
    # replicas so the replica's first wire poll finds a stream.
    for s in range(topo.shards):
        spawn(f"store{s}", "storenode", topo.shard_port(s),
              "--shard", str(s), "--index", "-1")
    for s in range(topo.shards):
        sup.wait_healthy(f"store{s}")
    for s in range(topo.shards):
        for r in range(topo.replicas):
            spawn(f"store{s}r{r}", "storenode", topo.replica_port(s, r),
                  "--shard", str(s), "--index", str(r))
    for s in range(topo.shards):
        for w in range(topo.workers):
            # drain_url: the supervisor's hard teardown drains workers
            # FIRST (wave 0) through this verb before any SIGTERM —
            # their in-flight deliveries finish, refused ones redeliver.
            from .workernode import DRAIN_PATH
            port = topo.worker_port(s, w)
            spawn(f"worker{s}.{w}", "workernode", port,
                  "--shard", str(s), "--index", str(w),
                  drain_url=f"http://{topo.host}:{port}{DRAIN_PATH}")
        for d in range(topo.dispatchers):
            spawn(f"dispatcher{s}.{d}", "dispatchernode",
                  topo.dispatcher_port(s, d),
                  "--shard", str(s), "--index", str(d))
    for g in range(topo.gateways):
        spawn(f"gateway{g}", "gatewaynode", topo.gateway_port(g),
              "--index", str(g))
    spawn("balancer", "balancer", topo.balancer_port())
    if topo.collector:
        # Last: its first scrape should find a healthy fleet, so a
        # boot-time unreachable gateway doesn't flip the conservation
        # check to advisory before traffic even starts.
        spawn("collector", "collector", topo.collector_port())
    for name in list(sup.children):
        sup.wait_healthy(name)


def _spawn_loadgens(topo: Topology, sup: Supervisor) -> list[str]:
    names = []
    for i in range(topo.loadgens):
        name = f"loadgen{i}"
        sup.spawn(name,
                  python_argv("ai4e_tpu.rig", "loadgen", "--spec",
                              topo.spec_path(), "--index", str(i)),
                  log_path=os.path.join(topo.workdir, f"{name}.log"))
        # Run-to-completion child: exiting is its JOB — the crash-loop
        # monitor must neither restart nor count it.
        sup.expect_death(name)
        names.append(name)
    return names


async def _await_loadgens(topo: Topology, sup: Supervisor,
                          names: list[str]) -> None:
    """Wait for every loadgen to exit — ramp + window + the bounded
    terminal drain, plus startup/flush headroom."""
    deadline = time.monotonic() + (topo.ramp + topo.duration
                                   + topo.task_timeout + 90.0)
    while time.monotonic() < deadline:
        if all(not sup.children[n].alive() for n in names):
            return
        # One monitor pass per second: restart crashed platform children
        # (bounded), raise on a crash-loop. Chaos kills and loadgen exits
        # are marked expected and skipped.
        restarted = sup.check()
        if restarted:
            log.warning("monitor restarted: %s", restarted)
        await asyncio.sleep(1.0)
    raise TimeoutError("loadgens did not finish inside their budget")


async def _drain_backlogs(topo: Topology, timeout: float) -> dict:
    """Poll every live shard node's ``/v1/taskstore/depths`` until no
    non-terminal work remains (or ``timeout``). Returns what was left."""
    import urllib.request

    def backlog() -> int:
        remaining = 0
        for s in range(topo.shards):
            for base in topo.shard_urls(s):
                try:
                    with urllib.request.urlopen(
                            base + "/v1/taskstore/depths",
                            timeout=5) as resp:
                        depths = json.loads(resp.read())
                except OSError:
                    continue  # dead node (chaos) — its replica answers
                remaining += sum(
                    counts.get("created", 0) + counts.get("running", 0)
                    for counts in depths.values())
                break  # one live node per shard is authoritative
        return remaining

    deadline = time.monotonic() + timeout
    left = await asyncio.to_thread(backlog)
    while left > 0 and time.monotonic() < deadline:
        await asyncio.sleep(2.0)
        left = await asyncio.to_thread(backlog)
    return {"drained": left == 0, "left": left}


async def _collect_observability(topo: Topology) -> dict:
    """Pre-teardown sweep of the fleet's memory-only observability
    state: hop ledgers (they die with the store processes), per-role
    vitals rings, flight-recorder rings, and the collector's live fleet
    snapshot. Everything best-effort — a chaos-killed node contributes
    nothing, which is itself recorded."""
    out: dict = {"ledgers": {}, "vitals": {}, "flight": {}, "fleet": None}

    def get(url: str):
        return asyncio.to_thread(_fetch_json, url, 5.0)

    # All fetches are independent — gather them (against saturated
    # survivors every endpoint can take seconds, and a serial sweep of
    # ~20 URLs would add tens of seconds before the verdict).
    async def shard_ledgers(s: int) -> dict:
        for base in topo.shard_urls(s):
            dump = await get(base + "/v1/rig/ledgers")
            if dump is not None:
                return dump.get("Ledgers", {})
            # next node: one live node per shard carries the timelines
        return {}

    urls = topo.metrics_urls()
    flight_names = [n for n in urls if n.startswith(("gateway", "store"))]
    fleet, ledger_dumps, vitals, flights = await asyncio.gather(
        (get(topo.collector_url() + "/v1/debug/fleet")
         if topo.collector else asyncio.sleep(0)),
        asyncio.gather(*(shard_ledgers(s) for s in range(topo.shards))),
        asyncio.gather(*(get(base + "/v1/debug/vitals")
                         for base in urls.values())),
        asyncio.gather(*(get(urls[n] + "/v1/debug/flight")
                         for n in flight_names)))
    out["fleet"] = fleet if topo.collector else None
    for dump in ledger_dumps:
        out["ledgers"].update(dump)
    for name, vit in zip(urls, vitals):
        if vit is not None and vit.get("recent"):
            out["vitals"][name] = vit["recent"]
    for name, flight in zip(flight_names, flights):
        if flight is not None and "entries" in flight:
            out["flight"][name] = flight
    return out


async def run_rig(topo: Topology, out_dir: str | None = None) -> dict:
    os.makedirs(topo.workdir, exist_ok=True)
    # A stale run's journals/windows would contaminate the verdict.
    for pattern in ("*.jsonl", "*.jsonl.replica*", "loadgen-*.json",
                    "*.log", "*.salvage.json", "timeline.json",
                    "fleet.json", "flight-*.json", "ledgers.json",
                    "vitals.json"):
        for path in glob.glob(os.path.join(topo.workdir, pattern)):
            os.unlink(path)
    topo.save(topo.spec_path())

    started_at = time.time()
    events = rig_chaos.build_timeline(topo) if topo.chaos else []
    result: dict = {"topology": topo.to_dict(), "started_at": started_at,
                    "chaos": events}
    with Supervisor(host=topo.host) as sup:
        _spawn_topology(topo, sup)
        log.info("topology up: %d processes", len(sup.children))
        names = _spawn_loadgens(topo, sup)
        window_opens_at = time.time() + topo.ramp
        chaos_task = None
        if events:
            chaos_task = asyncio.get_running_loop().create_task(
                rig_chaos.run_timeline(topo, sup, events, window_opens_at))
        rollout_task = None
        if topo.rollout:
            from . import rollout as rig_rollout
            rollout_task = asyncio.get_running_loop().create_task(
                rig_rollout.run_rollout(topo, sup, window_opens_at))
        try:
            await _await_loadgens(topo, sup, names)
        finally:
            if chaos_task is not None:
                chaos_task.cancel()
                try:
                    await chaos_task
                except asyncio.CancelledError:
                    pass
            if rollout_task is not None:
                # The upgrade should finish well inside the loadgen
                # window + drain budget; a wedged driver is cancelled and
                # recorded as such (the rollout gate then fails the run).
                try:
                    result["rollout"] = await asyncio.wait_for(
                        asyncio.shield(rollout_task), timeout=60.0)
                except (asyncio.TimeoutError, asyncio.CancelledError):
                    rollout_task.cancel()
                    result["rollout"] = {"scenario": topo.rollout,
                                         "outcome": "timed_out"}
        # Backlog drain: an accepted task's invariant is "eventually
        # terminal", and on a CPU-bound box the queues legitimately
        # outlive the loadgens. Wait (bounded) for every shard's created
        # backlog to hit zero BEFORE teardown, so the journals carry each
        # promise's resolution — a drain that times out leaves the stuck
        # tasks to the verdict, which is exactly what should fail then.
        result["drain"] = await _drain_backlogs(
            topo, timeout=float(topo.extra.get("drain_timeout_s", 120.0)))
        # Scrape while the survivors are still up; chaos-killed processes
        # are recorded as unreachable, which is itself evidence.
        result["metrics"] = rig_verdict.scrape_and_merge(
            rig_verdict.metrics_urls(topo))
        # The observability sweep must also beat teardown: hop ledgers,
        # vitals rings, and flight rings are memory-only state.
        observed = await _collect_observability(topo)
        result["fleet"] = observed["fleet"]
        loadgen_failures = [n for n in names
                            if sup.children[n].proc.returncode]
        result["loadgen_failures"] = loadgen_failures
    # Journals are scanned AFTER teardown: no writer left, every lineage
    # at its final byte.
    result["verdict"] = rig_verdict.compute_verdict(topo)
    result["finished_at"] = time.time()
    # The live collector's conservation cross-check feeds the verdict:
    # CONFIRMED breaches (terminal outcomes outran admissions with no
    # counter loss to excuse it) fail the run beside the journal
    # reconciliation; advisory ones (counters died with a chaos-killed
    # proc) are recorded but never gate — the journals stay
    # authoritative (docs/deployment.md).
    conservation = ((observed["fleet"] or {}).get("conservation")
                    or {"ok": True, "violations": []})
    result["verdict"]["conservation"] = conservation
    rollout_gate_ok = True
    if topo.rollout:
        from . import rollout as rig_rollout
        rollout_gate_ok, why = rig_rollout.rollout_ok(
            topo, result.get("rollout"))
        result.setdefault("rollout", {})["gate"] = {
            "ok": rollout_gate_ok, "reason": why}
        log.log(logging.INFO if rollout_gate_ok else logging.WARNING,
                "rollout gate: %s (%s)",
                "ok" if rollout_gate_ok else "FAILED", why)
    result["ok"] = bool(result["verdict"]["ok"]
                        and conservation.get("ok", True)
                        and not loadgen_failures
                        and rollout_gate_ok)
    _write_observability_artifacts(topo, result, observed, out_dir)
    if out_dir:
        os.makedirs(out_dir, exist_ok=True)
        out_path = os.path.join(out_dir, "rig.json")
        with open(out_path, "w", encoding="utf-8") as fh:
            json.dump(result, fh, indent=1)
        log.info("rig artifact written to %s", out_path)
    return result


def _write_observability_artifacts(topo: Topology, result: dict,
                                   observed: dict,
                                   out_dir: str | None) -> None:
    """The run as one loadable Perfetto timeline + the raw pieces. The
    artifact directory always gets them; on a RED verdict they ALSO
    land in the workdir beside the journals/logs — the teardown
    artifacts CI uploads, so a red run ships the timelines that explain
    it, not just the journals that convict it."""
    from ..observability.timeline import build_chrome_trace

    samples = {}
    for w in result.get("verdict", {}).get("windows", ()):
        if w.get("samples"):
            samples[f"loadgen{w.get('loadgen', '?')}"] = w["samples"]
    timeline = build_chrome_trace(observed["ledgers"],
                                  chaos=result.get("chaos"),
                                  vitals=observed["vitals"],
                                  loadgen_samples=samples)

    def dump_into(directory: str) -> None:
        os.makedirs(directory, exist_ok=True)

        def write(name: str, payload) -> None:
            with open(os.path.join(directory, name), "w",
                      encoding="utf-8") as fh:
                json.dump(payload, fh)

        write("timeline.json", timeline)
        write("ledgers.json", {"Ledgers": observed["ledgers"]})
        if result.get("rollout"):
            write("rollout.json", result["rollout"])
        write("vitals.json", observed["vitals"])
        if observed["fleet"] is not None:
            write("fleet.json", observed["fleet"])
        for name, flight in observed["flight"].items():
            write(f"flight-{name}.json", flight)

    if out_dir:
        dump_into(out_dir)
        log.info("timeline.json (%d tasks, %d procs) written to %s",
                 timeline["otherData"]["tasks"],
                 len(timeline["otherData"]["procs"]), out_dir)
    if not result["ok"]:
        dump_into(topo.workdir)
        log.warning("verdict violated: flight rings + fleet snapshot + "
                    "timeline dumped into %s", topo.workdir)


def summarize(result: dict) -> str:
    v = result["verdict"]
    offered = sum(w["window"]["offered_rate"] for w in v["windows"]
                  if w.get("window"))
    achieved = sum(w["window"]["achieved_rate"] for w in v["windows"]
                   if w.get("window"))
    lines = [
        f"rig {'OK' if result['ok'] else 'VIOLATED'}: "
        f"offered {offered:.0f}/s achieved {achieved:.0f}/s, "
        f"{v['accepted']} accepted, {v['terminal']} terminal, "
        f"{v['duplicates']} duplicate completions, "
        f"{v['violation_count']} violations"]
    for s, meta in sorted(v["per_shard"].items()):
        lines.append(
            f"  shard {s}: accepted={meta['accepted']} "
            f"terminal={meta['terminal']} dup={meta['duplicates']} "
            f"epochs={meta['epochs']} "
            f"{'promoted' if meta['promoted'] else 'primary held'} "
            f"(monotonic={meta['epochs_strictly_monotonic']})")
    for event in result.get("chaos", ()):
        lines.append(f"  chaos @+{event['at']}s {event['verb']} "
                     f"{'ok' if event.get('ok') else 'FAILED'}")
    rollout = result.get("rollout")
    if rollout:
        gate = rollout.get("gate", {})
        lines.append(
            f"  rollout [{rollout.get('scenario')}]: "
            f"{rollout.get('outcome')} "
            f"(weights {rollout.get('weight_history', [])}, "
            f"{len(rollout.get('upgraded', []))} upgraded, "
            f"{len(rollout.get('reverted', []))} reverted) — gate "
            f"{'ok' if gate.get('ok') else 'FAILED'}: "
            f"{gate.get('reason', '')}")
    cons = v.get("conservation")
    if cons is not None:
        lines.append(
            f"  fleet conservation: "
            f"{'ok' if cons.get('ok', True) else 'VIOLATED'} "
            f"({len(cons.get('violations', []))} recorded"
            f"{', degraded — counters lost with killed procs' if cons.get('degraded') else ''})")
    return "\n".join(lines)
