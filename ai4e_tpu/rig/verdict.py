"""Cross-process invariant verdict (docs/deployment.md).

The in-process chaos harness attaches an ``InvariantChecker`` to the
store's listener surface; across processes there is no shared listener —
but there IS something better: every shard's journal is the durable,
hash-chain-verified record of every transition that was ever
acknowledged. The rig verdict therefore reconciles three sources:

1. **the clients' promise set** — every TaskId a loadgen's POST was
   answered 200 with (``loadgen-*.json``), plus the terminal status the
   client itself observed;
2. **the shards' journal lineages** — for each shard, the authoritative
   transition history: the primary's journal, or — when a replica
   promoted — the promoted replica's journal (which contains the
   absorbed primary history verbatim plus its own post-promotion
   records). Terminal transitions, duplicate terminals, and the fencing
   epoch sequence are all read from here;
3. **every process's ``/metrics``** — scraped per role and merged into
   one coherent registry view (the per-role-registries half of the
   tentpole), saved beside the verdict.

The verdict object is the existing ``chaos.InvariantChecker`` — fed from
the journals instead of a listener — so "0 lost, 0 duplicated, per shard
and globally" means exactly what it means in ``tests/test_shard_chaos``.

One cross-process subtlety: a live ``move_slot`` journals the moved
records on BOTH shards (the source's original history + the
destination's import). An import applies without notifying — it is not a
client-visible transition — so a terminal record for the same (task,
status) appearing in a *different* shard's lineage is a migration copy,
not a duplicate; only a second terminal within one lineage (or a
conflicting terminal status anywhere) violates invariant 3.
"""

from __future__ import annotations

import glob
import json
import logging
import os
import urllib.request

from ..chaos.invariants import InvariantChecker
from ..taskstore import TaskNotFound, TaskStatus
from ..taskstore.journal import scan_journal
from ..taskstore.sharding import stable_hash
from ..taskstore.task import APITask
from .topology import Topology

log = logging.getLogger("ai4e_tpu.rig.verdict")


# -- journal lineages -------------------------------------------------------


def shard_lineage(topo: Topology, shard: int) -> tuple[str, bool]:
    """(journal path of the shard's authoritative lineage, promoted?).
    A replica journal containing an ``Epoch > 0`` record promoted itself
    and carries the full absorbed history + its own records; otherwise
    the primary's file is the lineage."""
    for r in range(topo.replicas):
        path = topo.replica_journal_path(shard, r)
        if not os.path.exists(path):
            continue
        scan = scan_journal(path, keep_records=True)
        if any(rec.get("Epoch", 0) > 0 for rec in scan.decoded
               if "Epoch" in rec):
            return path, True
    return topo.journal_path(shard), False


def _is_task_record(rec: dict) -> bool:
    # Full upsert records AND Slim status-transition records both carry
    # TaskId + Status and both represent one applied transition; Evict /
    # Result / Epoch records do not.
    return ("TaskId" in rec and "Status" in rec and "Epoch" not in rec
            and not rec.get("Evict") and not rec.get("Result"))


def scan_lineage(path: str) -> dict:
    """One shard lineage → ordered terminal transitions + epoch sequence
    + final task states."""
    if not os.path.exists(path):
        return {"terminals": [], "epochs": [], "final": {}, "records": 0,
                "clean": True}
    scan = scan_journal(path, keep_records=True)
    terminals: list[tuple[str, str]] = []   # (task_id, canonical) in order
    epochs: list[int] = []
    final: dict[str, APITask] = {}
    for rec in scan.decoded:
        if "Epoch" in rec:
            epochs.append(int(rec["Epoch"]))
            continue
        if not _is_task_record(rec):
            if rec.get("Evict"):
                final.pop(rec.get("TaskId", ""), None)
            continue
        task = APITask.from_dict(rec)
        final[task.task_id] = task
        if task.canonical_status in TaskStatus.TERMINAL:
            terminals.append((task.task_id, task.canonical_status))
    return {"terminals": terminals, "epochs": epochs, "final": final,
            "records": scan.records, "clean": scan.clean,
            "bad_reason": scan.bad_reason}


class _FinalStateStore:
    """Duck-typed store for ``InvariantChecker.violations``'s lost-vs-stuck
    probe: the union of every lineage's final states."""

    def __init__(self, lineages: list[dict]):
        self._tasks: dict[str, APITask] = {}
        for lin in lineages:
            self._tasks.update(lin["final"])

    def get(self, task_id: str) -> APITask:
        task = self._tasks.get(task_id)
        if task is None:
            raise TaskNotFound(task_id)
        return task

    def add_listener(self, _listener) -> None:  # checker.attach compat
        pass


# -- the verdict ------------------------------------------------------------


def compute_verdict(topo: Topology) -> dict:
    """Reconcile loadgen promises against the journal lineages; returns
    the verdict dict the rig artifact records (``ok`` gates CI)."""
    accepted: set[str] = set()
    client_terminal: dict[str, str] = {}
    loadgens = sorted(glob.glob(os.path.join(topo.workdir,
                                             "loadgen-*.json")))
    windows = []
    for path in loadgens:
        with open(path, encoding="utf-8") as fh:
            data = json.load(fh)
        accepted.update(data.get("accepted", ()))
        client_terminal.update(data.get("terminal", {}))
        windows.append({"loadgen": data.get("loadgen"),
                        **({"tenant": data["tenant"]}
                           if data.get("tenant") else {}),
                        "window": data.get("window"),
                        "samples": data.get("samples")})

    lineages = []
    per_shard_meta = {}
    for shard in range(topo.shards):
        path, promoted = shard_lineage(topo, shard)
        lin = scan_lineage(path)
        lin["shard"] = shard
        lineages.append(lin)
        per_shard_meta[shard] = {
            "lineage": path, "promoted": promoted,
            "records": lin["records"], "clean": lin["clean"],
            "epochs": lin["epochs"],
            "epochs_strictly_monotonic": all(
                b > a for a, b in zip(lin["epochs"], lin["epochs"][1:])),
        }

    def shard_of(task_id: str) -> int:
        # Initial ring assignment — stable attribution for the per-shard
        # verdict; a moved slot's tasks stay attributed to their origin
        # (the move itself is reported in the chaos timeline).
        return (stable_hash(task_id) % topo.slots) % topo.shards

    checker = InvariantChecker(shard_of=shard_of)
    checker.attach(_FinalStateStore(lineages))
    for tid in accepted:
        checker.note_accepted(tid)

    # Feed terminal transitions per lineage, filtering migration copies:
    # the FIRST occurrence of a given (task, status) in another lineage is
    # the import of an already-terminal task — not a second client-visible
    # completion. Everything else (a repeat within a lineage, a different
    # terminal status anywhere) feeds the checker as-is.
    seen_elsewhere: dict[str, str] = {}
    for lin in lineages:
        seen_here: set[str] = set()
        for tid, status in lin["terminals"]:
            prior = seen_elsewhere.get(tid)
            if prior == status and tid not in seen_here:
                seen_here.add(tid)
                continue  # migration copy from another shard's lineage
            seen_here.add(tid)
            checker.on_task_event(APITask(task_id=tid, status=status,
                                          backend_status=status))
        for tid, status in lin["terminals"]:
            seen_elsewhere.setdefault(tid, status)

    violations = checker.violations()
    by_shard = checker.by_shard()
    epoch_ok = all(m["epochs_strictly_monotonic"]
                   for m in per_shard_meta.values())
    journal_clean = all(lin["clean"] for lin in lineages)

    # Client-observed completions the journals never acknowledged would be
    # a durability lie in the other direction — check it explicitly.
    journal_terminal = {tid for lin in lineages
                        for tid, _ in lin["terminals"]}
    phantom = sorted(tid for tid, st in client_terminal.items()
                     if "completed" in st and tid not in journal_terminal)

    ok = (not violations and epoch_ok and journal_clean and not phantom)
    return {
        "ok": ok,
        "accepted": len(accepted),
        "terminal": len(checker.terminal),
        "duplicates": len(checker.duplicate_completions),
        "violations": violations[:50],
        "violation_count": len(violations),
        "phantom_client_completions": phantom[:20],
        "per_shard": {str(s): {**per_shard_meta[s],
                               **by_shard.get(s, {"accepted": 0,
                                                  "terminal": 0,
                                                  "duplicates": 0})}
                      for s in range(topo.shards)},
        "windows": windows,
    }


# -- per-role metrics scrape + merge ----------------------------------------
#
# The parse/merge core lives in observability/federation.py now — the
# LIVE FleetCollector (the collector rig role, `ai4e_tpu top`) and this
# post-hoc teardown merge are the same code; only the timing differs.


def scrape_and_merge(urls: dict[str, str],
                     timeout: float = 5.0) -> dict:
    """Scrape each role's ``/metrics`` and merge into one view: same
    (metric, labels) series SUM across processes — the single coherent
    metrics surface the one-process assembly used to get for free from
    its one registry. Returns ``{"merged": {...}, "per_role": {...},
    "unreachable": [...]}`` with merged keys rendered as
    ``name{labels}``."""
    from ..observability.federation import (merge_series, parse_prometheus,
                                            render_key)
    per_proc: dict[str, dict] = {}
    per_role: dict[str, int] = {}
    unreachable: list[str] = []
    for role, base in urls.items():
        try:
            with urllib.request.urlopen(base + "/metrics",
                                        timeout=timeout) as resp:
                series = parse_prometheus(
                    resp.read().decode("utf-8", "replace"))
        except OSError:
            # A chaos-killed process is SUPPOSED to be unreachable — the
            # merge records the gap instead of failing the scrape.
            unreachable.append(role)
            continue
        per_proc[role] = series
        per_role[role] = len(series)
    merged = merge_series(per_proc)
    return {"merged": {render_key(k): v for k, v in sorted(merged.items())},
            "per_role_series": per_role,
            "unreachable": unreachable}


def metrics_urls(topo: Topology) -> dict[str, str]:
    """Every scrapeable node in the topology, by role name (the
    topology owns the map; the live collector uses the same one)."""
    return topo.metrics_urls()
