"""Multi-process deployment rig (ISSUE 11 / ROADMAP item 4, docs/deployment.md).

Every bench since r6 carried the same caveat: the horizontal-scale story —
process-separable journals, per-shard fencing epochs, feed fan-out — had
only ever been exercised inside ONE process. This package runs the
platform as genuinely separate OS processes and replays the chaos
vocabulary against them at rate:

- ``topology.py``  — the resolved process/port layout, written to one JSON
  spec file every child derives its whole configuration from;
- ``supervisor.py`` — process supervision as a robustness surface: spawn,
  health-gate, crash-loop detection, port-conflict eviction, and a hard
  teardown that cannot leak processes (the lesson ``scripts/soak.sh``
  used to encode by hand);
- ``wire.py``      — the ring-routed store client gateway replicas,
  dispatcher pools, and workers share (slot-fence-aware re-routing, the
  wire change-feed tail), plus the wire broker the dispatcher processes
  pop leases from;
- ``storenode.py`` — one shard's store process (journaled primary or
  wire-tailing replica that promotes itself) with its broker and the
  rig's feed/broker/slot HTTP surfaces;
- ``gatewaynode.py`` / ``balancer.py`` / ``dispatchernode.py`` /
  ``workernode.py`` / ``loadgen.py`` — the remaining roles;
- ``chaos.py``     — the seeded fault timeline (gateway kill,
  shard-primary SIGKILL, live slot move, dispatcher kill) at rate;
- ``soak.py``      — ``scripts/soak.sh``'s engine on rig supervision
  (the script is now a thin CLI wrapper);
- ``verdict.py``   — the cross-process InvariantChecker verdict: client
  accept/terminal reconciliation + a journal-file scan for duplicate
  terminal transitions and fencing-epoch monotonicity, per shard and
  global, plus the per-role /metrics scrape-and-merge;
- ``run.py``       — the driver (``python -m ai4e_tpu.rig up``, ``make
  rig``) that assembles all of it and records the bench artifact.

The rig is pure opt-in: nothing here is imported by the single-process
assembly, and ``task_shards=1`` platforms are byte-identical with the rig
package present.
"""

from .supervisor import Supervisor  # noqa: F401
from .topology import Topology  # noqa: F401
