"""Fleet-telemetry collector PROCESS (docs/deployment.md collector row).

The observability plane's aggregation point: a ``FleetCollector``
(``observability/federation.py``) scraping every other role's
``/metrics`` on ``topo.scrape_interval``, serving:

- ``GET /v1/debug/fleet``          — the live fleet snapshot JSON
  (per-proc vitals/rates, fleet totals, the conservation cross-check) —
  what ``python -m ai4e_tpu top`` polls and what the rig driver saves
  beside the verdict;
- ``GET /v1/debug/fleet/metrics``  — the merged exposition with
  bounded-cardinality ``proc``/``role`` labels (point ONE Prometheus
  here instead of N+scattered ports);
- ``GET /metrics``                 — the collector's OWN registry
  (``ai4e_fleet_*`` + its vitals), scraped by the verdict like every
  role's.

The collector is an observer: chaos never targets it, and the fleet
serves identically without it (``--no-collector`` / ``collector=False``
— the observability-off identity claim, proven in tests)."""

from __future__ import annotations

import logging

from aiohttp import web

from ..metrics import MetricsRegistry
from ..observability.federation import FleetCollector
from .nodevitals import attach_vitals
from .topology import Topology

log = logging.getLogger("ai4e_tpu.rig.collector")

FLEET_PATH = "/v1/debug/fleet"


def build_collector_app(topo: Topology
                        ) -> tuple[web.Application, FleetCollector]:
    metrics = MetricsRegistry()
    targets = {name: url for name, url in topo.metrics_urls().items()
               if name != "collector"}
    collector = FleetCollector(targets,
                               interval_s=topo.scrape_interval,
                               metrics=metrics)
    app = web.Application()

    async def health(_: web.Request) -> web.Response:
        return web.json_response({"status": "healthy",
                                  "targets": len(targets)})

    async def own_metrics(_: web.Request) -> web.Response:
        return web.Response(text=metrics.render_prometheus(),
                            content_type="text/plain")

    async def fleet(_: web.Request) -> web.Response:
        return web.json_response(collector.snapshot())

    async def fleet_metrics(_: web.Request) -> web.Response:
        return web.Response(text=collector.render_merged(),
                            content_type="text/plain")

    app.router.add_get("/healthz", health)
    app.router.add_get("/metrics", own_metrics)
    app.router.add_get(FLEET_PATH, fleet)
    app.router.add_get(FLEET_PATH + "/metrics", fleet_metrics)
    attach_vitals(app, topo, metrics)

    async def start(_app) -> None:
        await collector.start()

    async def stop(_app) -> None:
        await collector.stop()

    app.on_startup.append(start)
    app.on_cleanup.append(stop)
    return app, collector


async def run_collectornode(topo: Topology) -> None:
    from .supervisor import serve_until_signal
    app, _collector = build_collector_app(topo)
    await serve_until_signal(app, topo.host, topo.collector_port())
