"""CPU-echo worker PROCESS — the rig's backend tier.

Deliberately the smallest honest backend: it receives the dispatcher's
POST, burns ``work_ms`` of CPU when the topology asks for service time,
stores the echoed payload as the task result and completes the task —
**conditionally** (``update_task_status_if created → completed``), the
remote-store-safe form of the terminal-clobber guard: a redelivered
execution racing the original can never produce a second client-visible
completion, which is exactly invariant 3 the chaos replay checks. All
store writes go through ``RingStoreClient``, so a task whose slot moved
mid-delivery lands its completion on the owning shard.
"""

from __future__ import annotations

import asyncio
import logging
import os
import random
import time

from aiohttp import web

from ..metrics import MetricsRegistry
from ..observability.ledger import EXECUTE, ledger_event
from ..rollout.canary import generation_label
from ..rollout.drain import DRAINING_HEADER, DrainState
from ..taskstore import TaskNotFound, TaskStatus
from .topology import Topology
from .wire import RingStoreClient

log = logging.getLogger("ai4e_tpu.rig.worker")

COMPLETED_STATUS = "completed by rig echo worker"

# The rig worker's drain/resume verbs — same shape as the production
# worker's (runtime/worker.py); the supervisor's teardown and the
# rolling-upgrade driver (rig/rollout.py) POST these.
DRAIN_PATH = "/v1/worker/drain"
RESUME_PATH = "/v1/worker/resume"

# Env var the rolling-upgrade driver bumps on respawn — which deploy
# generation this worker PROCESS serves (the rig analogue of
# ServableModel.generation).
GENERATION_ENV = "AI4E_ROLLOUT_GENERATION"


class EchoWorker:
    def __init__(self, topo: Topology, shard: int):
        self.topo = topo
        self.shard = shard
        self.metrics = MetricsRegistry()
        self.ring = RingStoreClient(topo.all_shard_urls(), slots=topo.slots)
        self._served = self.metrics.counter(
            "ai4e_rig_worker_requests_total",
            "Echo-worker deliveries by outcome")
        # --- rollout state (docs/deployment.md#rollouts) ------------------
        # Which deploy generation this PROCESS serves; the rolling-upgrade
        # driver bumps it through the supervisor's respawn env overrides.
        self.generation = int(os.environ.get(GENERATION_ENV, "1") or 1)
        self.drain_state = DrainState()
        self._inflight = 0
        self._rollout_outcomes = self.metrics.counter(
            "ai4e_rollout_outcomes_total",
            "Deliveries by deploy generation and outcome")
        self._drain_gauge = self.metrics.gauge(
            "ai4e_rollout_drain_state",
            "0 active, 1 draining, 2 drained")
        # Scenario B's bad canary: at the designated generation, fail a
        # seeded fraction of deliveries with a breaker-visible 500 so the
        # guard's burn/breaker signals have something real to trip on.
        self._error_rate = (topo.rollout_error_rate
                            if (topo.rollout_error_rate > 0
                                and self.generation
                                >= topo.rollout_bad_generation > 0)
                            else 0.0)
        self._err_rng = random.Random(
            f"{topo.seed}:{shard}:{self.generation}:bad-canary")
        if self._error_rate > 0:
            log.warning("worker shard %d generation %d: injecting %.0f%% "
                        "error rate (bad-canary scenario)",
                        shard, self.generation, self._error_rate * 100)
        self.app = web.Application(client_max_size=64 * 1024 * 1024)
        self.app.router.add_get("/healthz", self._health)
        self.app.router.add_get("/metrics", self._metrics)
        self.app.router.add_post(DRAIN_PATH, self._drain)
        self.app.router.add_get(DRAIN_PATH, self._drain_status)
        self.app.router.add_post(RESUME_PATH, self._resume)
        route = topo.route.rstrip("/")
        self.app.router.add_post(route, self._run)
        self.app.router.add_post(route + "/{tail:.*}", self._run)
        self.app.on_cleanup.append(self._cleanup)
        # Strong refs to in-flight fire-and-forget ledger stamps
        # (AIL004 — the loop holds tasks weakly).
        self._stamps: set[asyncio.Task] = set()

    async def _health(self, _: web.Request) -> web.Response:
        return web.json_response({"status": "healthy", "shard": self.shard,
                                  "generation": self.generation,
                                  "draining": self.drain_state.is_draining})

    async def _drain(self, request: web.Request) -> web.Response:
        """Graceful drain: stop admitting deliveries (503 + X-Draining so
        the dispatcher redelivers to a peer AND ejects us from placement),
        then wait — bounded — for in-flight deliveries to finish."""
        timeout_s = 5.0
        try:
            body = await request.json()
            if isinstance(body, dict) and "timeout_ms" in body:
                timeout_s = max(0.0, float(body["timeout_ms"]) / 1000.0)
        except (ValueError, TypeError):
            pass  # empty/non-JSON body — the default budget applies
        t0 = time.monotonic()
        self.drain_state.begin()
        self._drain_gauge.set(float(self.drain_state.state_code))
        while (time.monotonic() - t0 < timeout_s
               and (self._inflight > 0
                    or self.drain_state.reloads_in_flight > 0)):
            await asyncio.sleep(0.02)
        clean = self._inflight == 0
        self.drain_state.mark_drained()
        self._drain_gauge.set(float(self.drain_state.state_code))
        return web.json_response({
            "state": self.drain_state.state, "clean": clean,
            "inflight": self._inflight, "generation": self.generation,
            "drain_s": round(time.monotonic() - t0, 3)})

    async def _drain_status(self, _: web.Request) -> web.Response:
        return web.json_response({"state": self.drain_state.state,
                                  "inflight": self._inflight,
                                  "generation": self.generation})

    async def _resume(self, _: web.Request) -> web.Response:
        self.drain_state.resume()
        self._drain_gauge.set(float(self.drain_state.state_code))
        return web.json_response({"state": self.drain_state.state})

    async def _metrics(self, _: web.Request) -> web.Response:
        return web.Response(text=self.metrics.render_prometheus(),
                            content_type="text/plain")

    async def _cleanup(self, _app) -> None:
        await self.ring.aclose()

    async def _run(self, request: web.Request) -> web.Response:
        gen_label = generation_label(self.generation)
        if self.drain_state.is_draining:
            # Saturation-neutral refusal (503, not 5xx-failure): the
            # dispatcher redelivers this exact task to a peer, and the
            # X-Draining marker ejects us from placement WITHOUT opening
            # a breaker — draining is on purpose, not a fault.
            self._served.inc(outcome="draining")
            self._rollout_outcomes.inc(generation=gen_label,
                                       outcome="draining")
            return web.json_response(
                {"ok": False, "reason": "worker draining; retry a peer"},
                status=503,
                headers={"Retry-After": "1", DRAINING_HEADER: "1"})
        if self._error_rate > 0 and self._err_rng.random() < self._error_rate:
            # Bad-canary injection: a real failure (500) — breaker-visible
            # and burn-visible — and NO result write, so the redelivered
            # execution completes the task on a healthy generation.
            self._served.inc(outcome="injected_error")
            self._rollout_outcomes.inc(generation=gen_label,
                                       outcome="error")
            return web.json_response(
                {"ok": False, "reason": "injected canary fault"}, status=500)
        self._inflight += 1
        try:
            resp = await self._execute(request)
        finally:
            self._inflight -= 1
        if resp.status == 200:
            self._rollout_outcomes.inc(generation=gen_label, outcome="ok")
        return resp

    async def _execute(self, request: web.Request) -> web.Response:
        task_id = request.headers.get("taskId", "")
        body = await request.read()
        if not task_id:
            return web.json_response({"error": "taskId header required"},
                                     status=400)
        t0 = time.perf_counter()
        if self.topo.work_ms > 0:
            # Real CPU burn off the event loop — service time that actually
            # contends for the core, not a sleep that hides it.
            await asyncio.to_thread(self._burn, self.topo.work_ms / 1000.0)
        if self.topo.observability and len(self._stamps) < 256:
            # The worker's service-time slice on the task's timeline,
            # fire-and-forget to the owning shard node (the hot path at
            # rig rates must not wait on telemetry; beyond the in-flight
            # cap the stamp is dropped — a wedged shard must not
            # accumulate stamp tasks). ms-carrying events follow the
            # t-is-start contract (render_ledger/timeline.py compute
            # end = t + ms), so back-date t to the burn start.
            elapsed = time.perf_counter() - t0
            stamp = asyncio.get_running_loop().create_task(
                self.ring.append_ledger(task_id, [ledger_event(
                    EXECUTE, "worker", t=time.time() - elapsed,
                    ms=elapsed * 1e3)]))
            self._stamps.add(stamp)
            stamp.add_done_callback(self._stamps.discard)
        try:
            await self.ring.set_result(
                task_id, body or b"{}",
                content_type=request.content_type or "application/json")
        except TaskNotFound:
            # Unknown to every shard RIGHT NOW. That is either a truly
            # evicted task (no promise left) or a moved task mid-handoff
            # whose copy window outlasted the ring client's patience — a
            # 200 here would let the dispatcher complete the message and
            # strand the latter forever. 503 instead: the broker
            # redelivers with backoff, landing after the flip; a real
            # ghost exhausts its delivery budget and is dropped.
            self._served.inc(outcome="unknown_task")
            return web.json_response(
                {"ok": False, "reason": "unknown task"}, status=503,
                headers={"Retry-After": "1"})
        updated = await self.ring.update_task_status_if(
            task_id, TaskStatus.CREATED, COMPLETED_STATUS,
            TaskStatus.COMPLETED)
        if updated is None:
            # Already terminal — a duplicate delivery's write must NOT
            # land (invariant 3). The 200 still completes the message.
            self._served.inc(outcome="duplicate")
            return web.json_response({"ok": True, "duplicate": True})
        self._served.inc(outcome="completed")
        return web.json_response({"ok": True, "TaskId": task_id})

    @staticmethod
    def _burn(seconds: float) -> None:
        deadline = time.perf_counter() + seconds
        x = 0
        while time.perf_counter() < deadline:
            x += 1


class MeshEchoWorker(EchoWorker):
    """The meshworker role variant (``topo.mesh``, docs/mesh_serving.md):
    EchoWorker plus the mesh endpoint's health contract, driven by the
    SAME JAX-free state machine the production worker runs
    (``runtime/mesh/{spec,coordinator,redelivery}.py``) so the rig fleet
    chaos-proves it across real processes:

    - an injected poisoned delivery (``topo.mesh_poison_nths``, the rig
      analogue of ``AI4E_FAULT_MESH_POISON_NTHS``) answers **503
      result-invalidated** — saturation-neutral, so the broker
      redelivers exactly that task and breakers stay closed; the
      original never writes a result, so the redelivered execution's
      conditional completion can never double-complete (invariant 3);
    - ``unhealthy_after`` consecutive poisons flip ``EndpointHealth``
      and the worker answers **500** — a breaker *failure*, so the
      dispatcher ejects this endpoint and fails over to its peers —
      until ``mesh_recovery_s`` elapses and a probe delivery (the
      "follower restart") runs clean, which heals it.
    """

    def __init__(self, topo: Topology, shard: int):
        super().__init__(topo, shard)
        from ..runtime.mesh import (EndpointHealth, MeshCoordinator,
                                    parse_mesh_spec)
        self.layout = parse_mesh_spec(topo.mesh)
        self.health = EndpointHealth()
        # One virtual follower (process 1) carries the injected poison —
        # the same attribution the production endpoint's single-host
        # fault injection uses.
        self.coordinator = MeshCoordinator(self.layout, health=self.health,
                                           process_count=2)
        self._deliveries = 0
        self._poison_nths = frozenset(
            int(s) for s in topo.mesh_poison_nths.split(",") if s.strip())
        self._unhealthy_at = 0.0
        self._healthy_gauge = self.metrics.gauge(
            "ai4e_rig_mesh_healthy", "1 while the mesh endpoint is healthy")
        self._healthy_gauge.set(1.0)
        if self._poison_nths:
            log.warning("meshworker shard %d: poisoning deliveries %s",
                        shard, sorted(self._poison_nths))

    async def _health(self, _: web.Request) -> web.Response:
        body = {"status": "healthy", "shard": self.shard,
                "mesh": self.layout.describe(),
                "mesh_healthy": self.health.healthy}
        if not self.health.healthy:
            body["mesh_unhealthy_reason"] = self.health.reason
        # Always 200: the supervisor's liveness gate is process health;
        # mesh ejection is the DISPATCHER's breaker decision, driven by
        # the 500s below.
        return web.json_response(body)

    async def _run(self, request: web.Request) -> web.Response:
        if not self.health.healthy:
            if (time.monotonic() - self._unhealthy_at
                    < self.topo.mesh_recovery_s):
                # 500, not 503: resilience/health.py treats 503/429 as
                # saturation-neutral — only a >=500 failure opens the
                # dispatcher's breaker and ejects this endpoint.
                self._served.inc(outcome="unhealthy")
                return web.json_response(
                    {"ok": False, "reason": "mesh endpoint unhealthy: "
                                            + self.health.reason},
                    status=500)
            # Recovery window over — this delivery is the follower-restart
            # probe: fall through; a clean run heals via observe_poison.
        self._deliveries += 1
        if self._deliveries in self._poison_nths:
            was_healthy = self.health.healthy
            self.coordinator.observe_poison([0, 1])
            if was_healthy and not self.health.healthy:
                self._unhealthy_at = time.monotonic()
                self._healthy_gauge.set(0.0)
            self._served.inc(outcome="poisoned")
            return web.json_response(
                {"ok": False,
                 "reason": "result invalidated: a worker host degraded "
                           "while executing this row's shard"},
                status=503, headers={"Retry-After": "1"})
        self.coordinator.observe_poison([0, 0])
        self._healthy_gauge.set(1.0)
        return await super()._run(request)


async def run_workernode(topo: Topology, shard: int, index: int) -> None:
    from .nodevitals import attach_vitals
    from .supervisor import serve_until_signal
    worker = (MeshEchoWorker(topo, shard) if topo.mesh
              else EchoWorker(topo, shard))
    attach_vitals(worker.app, topo, worker.metrics)
    await serve_until_signal(worker.app, topo.host,
                             topo.worker_port(shard, index))
