"""CPU-echo worker PROCESS — the rig's backend tier.

Deliberately the smallest honest backend: it receives the dispatcher's
POST, burns ``work_ms`` of CPU when the topology asks for service time,
stores the echoed payload as the task result and completes the task —
**conditionally** (``update_task_status_if created → completed``), the
remote-store-safe form of the terminal-clobber guard: a redelivered
execution racing the original can never produce a second client-visible
completion, which is exactly invariant 3 the chaos replay checks. All
store writes go through ``RingStoreClient``, so a task whose slot moved
mid-delivery lands its completion on the owning shard.
"""

from __future__ import annotations

import asyncio
import logging
import time

from aiohttp import web

from ..metrics import MetricsRegistry
from ..observability.ledger import EXECUTE, ledger_event
from ..taskstore import TaskNotFound, TaskStatus
from .topology import Topology
from .wire import RingStoreClient

log = logging.getLogger("ai4e_tpu.rig.worker")

COMPLETED_STATUS = "completed by rig echo worker"


class EchoWorker:
    def __init__(self, topo: Topology, shard: int):
        self.topo = topo
        self.shard = shard
        self.metrics = MetricsRegistry()
        self.ring = RingStoreClient(topo.all_shard_urls(), slots=topo.slots)
        self._served = self.metrics.counter(
            "ai4e_rig_worker_requests_total",
            "Echo-worker deliveries by outcome")
        self.app = web.Application(client_max_size=64 * 1024 * 1024)
        self.app.router.add_get("/healthz", self._health)
        self.app.router.add_get("/metrics", self._metrics)
        route = topo.route.rstrip("/")
        self.app.router.add_post(route, self._run)
        self.app.router.add_post(route + "/{tail:.*}", self._run)
        self.app.on_cleanup.append(self._cleanup)
        # Strong refs to in-flight fire-and-forget ledger stamps
        # (AIL004 — the loop holds tasks weakly).
        self._stamps: set[asyncio.Task] = set()

    async def _health(self, _: web.Request) -> web.Response:
        return web.json_response({"status": "healthy", "shard": self.shard})

    async def _metrics(self, _: web.Request) -> web.Response:
        return web.Response(text=self.metrics.render_prometheus(),
                            content_type="text/plain")

    async def _cleanup(self, _app) -> None:
        await self.ring.aclose()

    async def _run(self, request: web.Request) -> web.Response:
        task_id = request.headers.get("taskId", "")
        body = await request.read()
        if not task_id:
            return web.json_response({"error": "taskId header required"},
                                     status=400)
        t0 = time.perf_counter()
        if self.topo.work_ms > 0:
            # Real CPU burn off the event loop — service time that actually
            # contends for the core, not a sleep that hides it.
            await asyncio.to_thread(self._burn, self.topo.work_ms / 1000.0)
        if self.topo.observability and len(self._stamps) < 256:
            # The worker's service-time slice on the task's timeline,
            # fire-and-forget to the owning shard node (the hot path at
            # rig rates must not wait on telemetry; beyond the in-flight
            # cap the stamp is dropped — a wedged shard must not
            # accumulate stamp tasks). ms-carrying events follow the
            # t-is-start contract (render_ledger/timeline.py compute
            # end = t + ms), so back-date t to the burn start.
            elapsed = time.perf_counter() - t0
            stamp = asyncio.get_running_loop().create_task(
                self.ring.append_ledger(task_id, [ledger_event(
                    EXECUTE, "worker", t=time.time() - elapsed,
                    ms=elapsed * 1e3)]))
            self._stamps.add(stamp)
            stamp.add_done_callback(self._stamps.discard)
        try:
            await self.ring.set_result(
                task_id, body or b"{}",
                content_type=request.content_type or "application/json")
        except TaskNotFound:
            # Unknown to every shard RIGHT NOW. That is either a truly
            # evicted task (no promise left) or a moved task mid-handoff
            # whose copy window outlasted the ring client's patience — a
            # 200 here would let the dispatcher complete the message and
            # strand the latter forever. 503 instead: the broker
            # redelivers with backoff, landing after the flip; a real
            # ghost exhausts its delivery budget and is dropped.
            self._served.inc(outcome="unknown_task")
            return web.json_response(
                {"ok": False, "reason": "unknown task"}, status=503,
                headers={"Retry-After": "1"})
        updated = await self.ring.update_task_status_if(
            task_id, TaskStatus.CREATED, COMPLETED_STATUS,
            TaskStatus.COMPLETED)
        if updated is None:
            # Already terminal — a duplicate delivery's write must NOT
            # land (invariant 3). The 200 still completes the message.
            self._served.inc(outcome="duplicate")
            return web.json_response({"ok": True, "duplicate": True})
        self._served.inc(outcome="completed")
        return web.json_response({"ok": True, "TaskId": task_id})

    @staticmethod
    def _burn(seconds: float) -> None:
        deadline = time.perf_counter() + seconds
        x = 0
        while time.perf_counter() < deadline:
            x += 1


class MeshEchoWorker(EchoWorker):
    """The meshworker role variant (``topo.mesh``, docs/mesh_serving.md):
    EchoWorker plus the mesh endpoint's health contract, driven by the
    SAME JAX-free state machine the production worker runs
    (``runtime/mesh/{spec,coordinator,redelivery}.py``) so the rig fleet
    chaos-proves it across real processes:

    - an injected poisoned delivery (``topo.mesh_poison_nths``, the rig
      analogue of ``AI4E_FAULT_MESH_POISON_NTHS``) answers **503
      result-invalidated** — saturation-neutral, so the broker
      redelivers exactly that task and breakers stay closed; the
      original never writes a result, so the redelivered execution's
      conditional completion can never double-complete (invariant 3);
    - ``unhealthy_after`` consecutive poisons flip ``EndpointHealth``
      and the worker answers **500** — a breaker *failure*, so the
      dispatcher ejects this endpoint and fails over to its peers —
      until ``mesh_recovery_s`` elapses and a probe delivery (the
      "follower restart") runs clean, which heals it.
    """

    def __init__(self, topo: Topology, shard: int):
        super().__init__(topo, shard)
        from ..runtime.mesh import (EndpointHealth, MeshCoordinator,
                                    parse_mesh_spec)
        self.layout = parse_mesh_spec(topo.mesh)
        self.health = EndpointHealth()
        # One virtual follower (process 1) carries the injected poison —
        # the same attribution the production endpoint's single-host
        # fault injection uses.
        self.coordinator = MeshCoordinator(self.layout, health=self.health,
                                           process_count=2)
        self._deliveries = 0
        self._poison_nths = frozenset(
            int(s) for s in topo.mesh_poison_nths.split(",") if s.strip())
        self._unhealthy_at = 0.0
        self._healthy_gauge = self.metrics.gauge(
            "ai4e_rig_mesh_healthy", "1 while the mesh endpoint is healthy")
        self._healthy_gauge.set(1.0)
        if self._poison_nths:
            log.warning("meshworker shard %d: poisoning deliveries %s",
                        shard, sorted(self._poison_nths))

    async def _health(self, _: web.Request) -> web.Response:
        body = {"status": "healthy", "shard": self.shard,
                "mesh": self.layout.describe(),
                "mesh_healthy": self.health.healthy}
        if not self.health.healthy:
            body["mesh_unhealthy_reason"] = self.health.reason
        # Always 200: the supervisor's liveness gate is process health;
        # mesh ejection is the DISPATCHER's breaker decision, driven by
        # the 500s below.
        return web.json_response(body)

    async def _run(self, request: web.Request) -> web.Response:
        if not self.health.healthy:
            if (time.monotonic() - self._unhealthy_at
                    < self.topo.mesh_recovery_s):
                # 500, not 503: resilience/health.py treats 503/429 as
                # saturation-neutral — only a >=500 failure opens the
                # dispatcher's breaker and ejects this endpoint.
                self._served.inc(outcome="unhealthy")
                return web.json_response(
                    {"ok": False, "reason": "mesh endpoint unhealthy: "
                                            + self.health.reason},
                    status=500)
            # Recovery window over — this delivery is the follower-restart
            # probe: fall through; a clean run heals via observe_poison.
        self._deliveries += 1
        if self._deliveries in self._poison_nths:
            was_healthy = self.health.healthy
            self.coordinator.observe_poison([0, 1])
            if was_healthy and not self.health.healthy:
                self._unhealthy_at = time.monotonic()
                self._healthy_gauge.set(0.0)
            self._served.inc(outcome="poisoned")
            return web.json_response(
                {"ok": False,
                 "reason": "result invalidated: a worker host degraded "
                           "while executing this row's shard"},
                status=503, headers={"Retry-After": "1"})
        self.coordinator.observe_poison([0, 0])
        self._healthy_gauge.set(1.0)
        return await super()._run(request)


async def run_workernode(topo: Topology, shard: int, index: int) -> None:
    from .nodevitals import attach_vitals
    from .supervisor import serve_until_signal
    worker = (MeshEchoWorker(topo, shard) if topo.mesh
              else EchoWorker(topo, shard))
    attach_vitals(worker.app, topo, worker.metrics)
    await serve_until_signal(worker.app, topo.host,
                             topo.worker_port(shard, index))
