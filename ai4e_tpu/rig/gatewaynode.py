"""One gateway replica PROCESS (docs/deployment.md).

The ordinary ``Gateway`` class over the rig's ``RingStoreClient`` instead
of an in-process store: every store verb crosses the task-store HTTP
surface ring-routed by TaskId, and the long-poll parks on the locally
tailed wire change feed — so a replica that did NOT admit a task still
wakes its long-poll with the record (the satellite regression in
``tests/test_longpoll.py`` proves the mechanism; the rig exercises it
across real processes). Each gateway carries its own per-role
``MetricsRegistry``; the rig's fleet collector (and the verdict's
post-hoc merge) scrape every node's ``/metrics`` into one coherent view.

Observability (``topo.observability``, default on): the gateway gets the
same ``RequestObservability`` hub the single-process assembly wires —
``admitted``/``published`` hop-ledger stamps ride fire-and-forget wire
appends to the OWNING shard node (the timeline lives beside the record),
refusals feed a local flight-recorder ring served at
``GET /v1/debug/flight``, and a vitals sampler exports
``ai4e_process_*``. The store-side half (terminal stamps, e2e latency,
outcome counters) lives on the shard nodes, which own the records.
"""

from __future__ import annotations

import logging

from ..gateway.router import Gateway
from ..metrics import MetricsRegistry
from .nodevitals import attach_vitals
from .topology import Topology
from .wire import RingStoreClient

log = logging.getLogger("ai4e_tpu.rig.gateway")


def build_gateway(topo: Topology) -> tuple[Gateway, RingStoreClient]:
    ring = RingStoreClient(topo.all_shard_urls(), slots=topo.slots)
    gateway = Gateway(ring, metrics=MetricsRegistry())
    # The recorded task Endpoint is nominal (dispatchers rebase onto their
    # shard's worker set); its PATH is what names the broker queue.
    gateway.add_async_route(topo.route, topo.worker_urls(0)[0])
    if topo.tenants:
        # Per-replica tenancy edge: THIS process resolves subscription
        # keys and enforces the token-bucket quota locally (no shared
        # bucket across gateways — the fleet admits up to gateways × rps
        # per tenant, the per-instance rate-limit semantic stated in
        # docs/tenancy.md). Outcome accounting stays on the record-owning
        # side; the edge counters (ai4e_tenant_admissions_total) land in
        # this node's registry and merge in the verdict scrape.
        from ..tenancy import Tenancy
        gateway.set_tenancy(Tenancy.from_spec(topo.tenants,
                                              metrics=gateway.metrics))
    if topo.observability:
        from ..observability.flight import FlightRecorder
        from ..observability.hub import RequestObservability
        gateway.set_observability(RequestObservability(
            ring, metrics=gateway.metrics,
            flight=FlightRecorder(capacity=256, metrics=gateway.metrics)))
    return gateway, ring


async def run_gatewaynode(topo: Topology, index: int) -> None:
    from .supervisor import serve_until_signal

    gateway, ring = build_gateway(topo)
    attach_vitals(gateway.app, topo, gateway.metrics)

    async def start_tails(_app) -> None:
        await ring.start_feed_tails()

    async def stop_tails(_app) -> None:
        await ring.aclose()

    gateway.app.on_startup.append(start_tails)
    gateway.app.on_cleanup.append(stop_tails)
    await serve_until_signal(gateway.app, topo.host,
                             topo.gateway_port(index))
