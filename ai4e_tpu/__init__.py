"""ai4e_tpu — a TPU-native model-serving API platform.

A brand-new framework with the capabilities of the AI for Earth API Platform
(reference: CSA-DanielVillamizar/AIforEarth-API-Platform), re-designed TPU-first:

- ``taskstore``  — durable task state machine (created → running → completed/failed)
  with per-endpoint status sets, the equivalent of the reference's Redis-backed
  CacheManager (``ProcessManager/CacheManager/CacheConnectorUpsert.cs:40-213``).
- ``broker``     — per-endpoint durable queues + dispatcher with 429 backpressure
  and redelivery (``ProcessManager/BackendQueueProcessor/BackendQueueProcessor.cs:27-81``).
- ``service``    — the in-container API service framework: sync/async endpoint
  decorators, concurrency caps, health, draining
  (``APIs/1.0/base-py/ai4e_service.py:44-213``).
- ``gateway``    — edge router: task creation at the edge, ``/task/{id}`` polling,
  sync pass-through (``APIManagement/request_policy.xml``).
- ``runtime``    — the genuinely new layer: JAX device-mesh manager, micro-batcher
  packing queued tasks into fixed-shape device batches, pjit-compiled model
  execution, compile cache.
- ``models``     — flagship model families (land-cover segmentation UNet,
  ResNet-50 classifier, MegaDetector-style detector) in Flax.
- ``ops``        — Pallas TPU kernels for hot ops.
- ``parallel``   — mesh/sharding helpers, XLA collectives, ring attention for
  long-context, multi-host utilities.
- ``metrics``    — in-flight/queue-depth gauges feeding the autoscaler signal
  (``ProcessManager/RequestReporter``).
- ``train``      — fine-tuning support: sharded train step over a device mesh.
"""

__version__ = "0.1.0"
