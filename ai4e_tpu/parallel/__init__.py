from .sharding import (
    AXES,
    MeshSpec,
    batch_sharding,
    init_distributed,
    make_mesh,
    pad_to_multiple,
    replicated,
    shard_params,
    spec_for_param,
)

__all__ = [
    "AXES",
    "MeshSpec",
    "batch_sharding",
    "init_distributed",
    "make_mesh",
    "pad_to_multiple",
    "replicated",
    "shard_params",
    "spec_for_param",
]
