"""Multi-host serving bridge — request ingestion on host 0, SPMD on all hosts.

SURVEY.md §7 hard part #3: a TPU pod slice spans hosts and every process must
enter the same ``pjit`` calls in the same order, but only host 0 fronts the
gateway/broker. The reference has no analogue (its NCCL-equivalent plane was
HTTPS+queues between single-GPU containers, SURVEY.md §5 "distributed
communication backend"); this is the genuinely-new data plane.

Design (the jax.distributed idiom):

- every process calls ``init_distributed`` (``parallel.sharding``) so
  ``jax.devices()`` spans the slice, then builds the same ``Mesh``;
- the **primary** (process 0) runs the platform stack (gateway, broker,
  batcher). Its batcher executes through ``MultihostRuntime.run_batch`` which
  first *broadcasts* a work descriptor (model index + real batch) over DCN
  (``multihost_utils.broadcast_one_to_all``), then enters the model's
  compiled call — which every process enters too;
- **followers** run ``follower_loop()``: block on the same broadcast, enter
  the same call, loop. A sentinel descriptor shuts them down;
- outputs come back replicated (inference outputs are small — class ids,
  boxes, counts), so the primary reads results locally with no gather on the
  response path.

The broadcast rides XLA's collectives; there is no bespoke socket protocol —
the "communication backend" is jax.distributed + XLA over ICI/DCN exactly as
a TPU-native design should be.
"""

from __future__ import annotations

import logging

import jax
import numpy as np

log = logging.getLogger("ai4e_tpu.multihost")

_SHUTDOWN = -1
# Fixed-rank shape header so the control broadcast is always the same shape
# (broadcast_one_to_all requires identical pytree structure on every host).
_MAX_RANK = 8


def is_primary() -> bool:
    return jax.process_index() == 0


class MultihostRuntime:
    """Wraps a ``ModelRuntime`` so batch execution is SPMD across hosts.

    Single-host (``jax.process_count() == 1``) it is a transparent
    pass-through — the batcher uses one code path everywhere.
    """

    def __init__(self, runtime):
        self.runtime = runtime
        # Stable model ordering shared by all hosts: registration order.
        self._names = list(runtime.models)
        # The batcher may pipeline two batches on separate executor threads;
        # followers replay broadcasts strictly in order, so the primary's
        # descriptor+batch+execute sequence must be serialised.
        import threading
        self._order_lock = threading.Lock()

    # Pass-throughs so the micro-batcher (and launcher logging) can treat
    # this exactly like a ModelRuntime.
    @property
    def models(self):
        return self.runtime.models

    @property
    def mesh(self):
        return self.runtime.mesh

    def _model_index(self, name: str) -> int:
        # No refresh-on-miss: followers' name tables are frozen at
        # construction, so a model registered after the wrap could never be
        # resolved consistently across hosts — fail fast on the primary.
        try:
            return self._names.index(name)
        except ValueError:
            raise KeyError(
                f"model {name!r} registered after MultihostRuntime was "
                "built; register every model before wrapping") from None

    # -- primary side (called by the micro-batcher's executor thread) -------

    def run_batch(self, model_name: str, batch: np.ndarray):
        if jax.process_count() == 1:
            return self.runtime.run_batch(model_name, batch)
        if not is_primary():
            raise RuntimeError(
                "run_batch on a follower host — followers run follower_loop()")
        with self._order_lock:
            self._broadcast_descriptor(self._model_index(model_name), batch)
            _ = self._broadcast_batch(batch)
            return self.runtime.run_batch(model_name, batch)

    def shutdown_followers(self) -> None:
        if jax.process_count() > 1 and is_primary():
            with self._order_lock:
                self._broadcast_descriptor(_SHUTDOWN, None)

    # -- follower side -------------------------------------------------------

    def follower_loop(self) -> None:
        """Run on every non-primary process: mirror the primary's batch
        executions until the shutdown sentinel arrives."""
        assert not is_primary(), "primary must not enter follower_loop"
        while True:
            model_idx, shape, dtype = self._receive_descriptor()
            if model_idx == _SHUTDOWN:
                log.info("follower %d: shutdown", jax.process_index())
                return
            batch = self._broadcast_batch(
                np.zeros(shape, dtype))  # payload comes from the broadcast
            name = self._names[model_idx]
            try:
                self.runtime.run_batch(name, batch)
            except Exception:  # noqa: BLE001 — mirror the primary's policy
                # The primary catches the same device failure and keeps
                # serving (MicroBatcher._execute); a follower that died here
                # would leave the next broadcast waiting on a missing
                # participant and hang the whole slice.
                log.exception("follower %d: batch for %s failed; continuing",
                              jax.process_index(), name)

    # -- wire (XLA collectives over DCN) ------------------------------------

    def _broadcast_descriptor(self, model_idx: int, batch) -> None:
        from jax.experimental import multihost_utils
        header = np.full((2 + _MAX_RANK,), 0, np.int32)
        header[0] = model_idx
        if batch is not None:
            header[1] = _dtype_code(batch.dtype)
            rank = batch.ndim
            header[2:2 + rank] = batch.shape
        multihost_utils.broadcast_one_to_all(header)

    def _receive_descriptor(self):
        from jax.experimental import multihost_utils
        header = np.asarray(multihost_utils.broadcast_one_to_all(
            np.zeros((2 + _MAX_RANK,), np.int32)))
        model_idx = int(header[0])
        if model_idx == _SHUTDOWN:
            return model_idx, None, None
        shape = tuple(int(d) for d in header[2:] if d > 0)
        return model_idx, shape, _code_dtype(int(header[1]))

    def _broadcast_batch(self, batch: np.ndarray) -> np.ndarray:
        from jax.experimental import multihost_utils
        return np.asarray(multihost_utils.broadcast_one_to_all(batch))


_DTYPES = [np.float32, np.float16, np.uint8, np.int32, np.int8]


def _dtype_code(dtype) -> int:
    for i, d in enumerate(_DTYPES):
        if np.dtype(dtype) == np.dtype(d):
            return i
    raise ValueError(f"unsupported broadcast dtype {dtype}")


def _code_dtype(code: int):
    return np.dtype(_DTYPES[code])
