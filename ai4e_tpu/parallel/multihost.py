"""Multi-host serving bridge — request ingestion on host 0, SPMD on all hosts.

SURVEY.md §7 hard part #3: a TPU pod slice spans hosts and every process must
enter the same ``pjit`` calls in the same order, but only host 0 fronts the
gateway/broker. The reference has no analogue (its NCCL-equivalent plane was
HTTPS+queues between single-GPU containers, SURVEY.md §5 "distributed
communication backend"); this is the genuinely-new data plane.

Design (the jax.distributed idiom), v2 — sharded ingestion:

- every process calls ``init_distributed`` (``parallel.sharding``) so
  ``jax.devices()`` spans the slice, then builds the same ``Mesh``;
- the **primary** (process 0) runs the platform stack (gateway, broker,
  batcher). Its batcher executes through ``MultihostRuntime.run_batch``:
  it stages each follower's *own rows* of the batch on a host-local shard
  feed, broadcasts a small fixed-size work descriptor (model index, sequence
  number, shape) over DCN (``multihost_utils.broadcast_one_to_all``), then
  enters the model's compiled call — which every process enters too;
- **followers** run ``follower_loop()``: block on the descriptor, fetch only
  the rows their addressable devices own from the primary's feed (an HTTP GET
  over the control network — batch/N bytes, not the whole batch), assemble
  the global device array with ``jax.make_array_from_single_device_arrays``,
  and enter the same call. A sentinel descriptor shuts them down;
- outputs come back replicated (inference outputs are small — class ids,
  boxes, counts), so the primary reads results locally with no gather on the
  response path.

Why not ``multihost_utils.broadcast_one_to_all`` for the payload (the v1
design): that replicates the *full* batch to every host through a collective
— O(N x batch) traffic serialized behind host 0, exactly the "must not
serialize on DCN" failure mode SURVEY.md §7 hard part #3 calls out. Since
only host 0 ingests requests, batch bytes must leave host 0 once; the feed
ships each follower only its shard (sum = one batch, the minimum), the
fetches run in parallel, and the collective carries 13 ints. The descriptor
broadcast still rides XLA's collectives, which also keeps the SPMD program
order aligned across processes.
"""

from __future__ import annotations

import logging
import threading
import time

import jax
import numpy as np

log = logging.getLogger("ai4e_tpu.multihost")

_SHUTDOWN = -1
# Fixed-rank shape header so the control broadcast is always the same shape
# (broadcast_one_to_all requires identical pytree structure on every host).
_MAX_RANK = 8
# Staged shards older than this many sequence numbers are pruned (a follower
# that died mid-fetch must not leak primary memory forever).
_FEED_WINDOW = 8


def is_primary() -> bool:
    return jax.process_index() == 0


def _fault_fetch_nths() -> frozenset[int]:
    """Fault-injection knob: 1-based shard-fetch ordinals this follower
    should fail (comma-separated in AI4E_FAULT_FETCH_FAIL_NTHS). Empty in
    production; tests use it to drive the degradation path end to end."""
    import os
    raw = os.environ.get("AI4E_FAULT_FETCH_FAIL_NTHS", "")
    return frozenset(int(s) for s in raw.split(",") if s.strip())


class _ShardFeed:
    """Host-local HTTP server on the primary staging per-follower batch rows.

    One GET per (sequence, process): ``/shard/{seq}/{proc}`` -> raw bytes.
    Entries live until ``_FEED_WINDOW`` newer batches have been staged, so a
    retried fetch (dropped connection) still succeeds.
    """

    def __init__(self, token: bytes):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        feed = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (http.server API)
                # Bearer-token gate: the feed serves raw request payloads,
                # and binds wide so followers reach it over DCN — anything
                # without the slice's construction-time token gets 403.
                import hmac
                if not hmac.compare_digest(
                        self.headers.get("X-AI4E-Feed-Token", ""),
                        feed.token_str):
                    self.send_response(403)
                    self.end_headers()
                    return
                parts = self.path.strip("/").split("/")
                payload = None
                if len(parts) == 3 and parts[0] == "shard":
                    with feed._lock:
                        payload = feed._staged.get(
                            (int(parts[1]), int(parts[2])))
                if payload is None:
                    self.send_response(404)
                    self.end_headers()
                    return
                self.send_response(200)
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def log_message(self, *a):  # quiet
                pass

        self.token_str = token.hex()
        self._staged: dict[tuple[int, int], bytes] = {}
        self._lock = threading.Lock()
        self._server = ThreadingHTTPServer(("0.0.0.0", 0), Handler)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        name="ai4e-shard-feed", daemon=True)
        self._thread.start()

    def stage(self, seq: int, proc: int, payload: bytes) -> None:
        with self._lock:
            self._staged[(seq, proc)] = payload
            for key in [k for k in self._staged if k[0] <= seq - _FEED_WINDOW]:
                del self._staged[key]

    def shutdown(self) -> None:
        self._server.shutdown()
        self._server.server_close()


def _fetch(url: str, token: str, timeout_s: float = 60.0) -> bytes:
    """GET with retry — the shard is staged before the descriptor broadcast,
    so 404 only means a transient reordering/hiccup, not absence.

    Sync-only path, verified for AIL001: called exclusively from
    ``follower_loop()`` — a blocking SPMD loop that runs in the follower
    process's MAIN thread, where no event loop exists (followers run no
    asyncio at all; the primary's platform stack never calls this). The
    ``time.sleep`` backoff below is therefore correct as-is; converting it
    to ``asyncio.sleep`` would require an event loop the caller
    deliberately does not have."""
    import urllib.error
    import urllib.request

    deadline = time.monotonic() + timeout_s
    delay = 0.02
    while True:
        try:
            req = urllib.request.Request(
                url, headers={"X-AI4E-Feed-Token": token})
            with urllib.request.urlopen(req, timeout=10) as resp:
                return resp.read()
        except (urllib.error.URLError, OSError) as e:
            if time.monotonic() >= deadline:
                raise TimeoutError(f"shard fetch {url} failed: {e}") from e
            time.sleep(delay)
            delay = min(delay * 2, 0.5)


def _dim0_range(idx, global_shape) -> tuple[int, int]:
    s0 = idx[0] if idx else slice(None)
    start = s0.start if s0.start is not None else 0
    stop = s0.stop if s0.stop is not None else global_shape[0]
    return int(start), int(stop)


def _rows_by_process(sharding, global_shape) -> dict[int, list[tuple[int, int]]]:
    """dim-0 row ranges each process's devices own, deduped and sorted.

    Batch shardings split only the leading dim (``registry.py`` shards
    ``P(("dp","fsdp"), None...)``); replicated axes (tp/sp/ep) make several
    devices own identical ranges — deduped here so a host never receives the
    same rows twice.
    """
    per: dict[int, set] = {}
    for d, idx in sharding.devices_indices_map(global_shape).items():
        for s in idx[1:]:
            assert s.start in (None, 0) and s.stop in (None,) + tuple(
                global_shape[1:]), (
                f"non-batch dim sharded in {idx}; shard feed only splits dim 0")
        per.setdefault(d.process_index, set()).add(
            _dim0_range(idx, global_shape))
    return {p: sorted(v) for p, v in per.items()}


class MultihostRuntime:
    """Wraps a ``ModelRuntime`` so batch execution is SPMD across hosts.

    Single-host (``jax.process_count() == 1``) it is a transparent
    pass-through — the batcher uses one code path everywhere.
    """

    def __init__(self, runtime):
        self.runtime = runtime
        # Stable model ordering shared by all hosts: registration order.
        self._names = list(runtime.models)
        # The batcher may pipeline two batches on separate executor threads;
        # followers replay broadcasts strictly in order, so the primary's
        # stage+descriptor+execute sequence must be serialised.
        self._order_lock = threading.Lock()
        self._seq = 0
        self._plans: dict[tuple[str, tuple], dict] = {}
        self._feed = None
        self._feed_url = None
        # Observability for the "don't serialize on DCN" requirement:
        # bytes the primary shipped for the last batch / in total, and the
        # last ingest (stage+descriptor or fetch+assemble) wall seconds.
        self.last_egress_bytes = 0
        self.total_egress_bytes = 0
        self.last_ingest_s = 0.0
        self._fetch_count = 0  # fault-injection ordinal (follower side)
        # Mesh serving plane hooks (runtime/mesh/, docs/mesh_serving.md):
        # ``poison_listener(flags)`` receives the per-process poison flags
        # of every gather (the coordinator's follower-health signal), and
        # ``_process_phases`` accumulates (label, process_index, seconds)
        # device-phase tuples per batch — staged-shard egress per follower
        # plus the primary's assemble and execute — drained by the mesh
        # endpoint into per-request hop ledgers. Both are fail-open
        # telemetry; under pipelined batches drain attribution can lag one
        # batch (the order lock serialises the executions themselves).
        self.poison_listener = None
        self._process_phases: list[tuple[str, int, float]] = []
        # Own lock (not _order_lock): drain runs on the event loop and
        # must never wait out a whole device execution.
        self._phases_lock = threading.Lock()
        if jax.process_count() > 1:
            self._open_feed()

    # Pass-throughs so the micro-batcher (and launcher logging) can treat
    # this exactly like a ModelRuntime.
    @property
    def models(self):
        return self.runtime.models

    @property
    def mesh(self):
        return self.runtime.mesh

    def _open_feed(self) -> None:
        """Primary opens the shard feed; everyone learns its address and the
        feed's bearer token via one construction-time collective (port +
        advertise IP + 16 token bytes as int32s)."""
        import os
        import socket

        from jax.experimental import multihost_utils

        addr = np.zeros((21,), np.int32)
        if is_primary():
            token = os.urandom(16)
            self._feed = _ShardFeed(token)
            ip = os.environ.get("AI4E_FEED_ADVERTISE_IP")
            if not ip:
                try:
                    with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
                        s.connect(("8.8.8.8", 80))  # no packet sent (UDP)
                        ip = s.getsockname()[0]
                except OSError:
                    ip = "127.0.0.1"
            addr[0] = self._feed.port
            addr[1:5] = [int(o) for o in ip.split(".")]
            addr[5:21] = np.frombuffer(token, np.uint8)
        addr = np.asarray(multihost_utils.broadcast_one_to_all(addr))
        self._feed_url = (f"http://{addr[1]}.{addr[2]}.{addr[3]}.{addr[4]}"
                          f":{addr[0]}")
        self._feed_token = bytes(addr[5:21].astype(np.uint8)).hex()

    def _model_index(self, name: str) -> int:
        # No refresh-on-miss: followers' name tables are frozen at
        # construction, so a model registered after the wrap could never be
        # resolved consistently across hosts — fail fast on the primary.
        try:
            return self._names.index(name)
        except ValueError:
            raise KeyError(
                f"model {name!r} registered after MultihostRuntime was "
                "built; register every model before wrapping") from None

    def _plan(self, name: str, global_shape: tuple):
        key = (name, tuple(global_shape))
        if key not in self._plans:
            sharding = self.runtime.models[name]._batch_sharding
            self._plans[key] = _rows_by_process(sharding, global_shape)
        return self._plans[key]

    def _assemble(self, name: str, global_shape, dtype, rows_lookup):
        """Build the global device array from this process's rows only."""
        sharding = self.runtime.models[name]._batch_sharding
        arrays = []
        amap = sharding.addressable_devices_indices_map(tuple(global_shape))
        for d, idx in amap.items():
            start, stop = _dim0_range(idx, global_shape)
            arrays.append(jax.device_put(rows_lookup(start, stop), d))
        return jax.make_array_from_single_device_arrays(
            tuple(global_shape), sharding, arrays)

    # -- primary side (called by the micro-batcher's executor thread) -------

    def run_batch(self, model_name: str, batch: np.ndarray):
        return self.run_batch_report(model_name, batch)[0]

    def run_batch_report(self, model_name: str, batch: np.ndarray
                         ) -> tuple[object, frozenset]:
        """Execute one batch; returns ``(outputs, poisoned_rows)`` where
        ``poisoned_rows`` are global dim-0 indices whose results are invalid
        because a follower degraded (fetch failure → zeros shard, or a
        follower-local execution failure). The batcher fails exactly those
        tasks instead of serving confidently wrong results (VERDICT r2 #5)."""
        if jax.process_count() == 1:
            return self.runtime.run_batch(model_name, batch), frozenset()
        if not is_primary():
            raise RuntimeError(
                "run_batch on a follower host — followers run follower_loop()")
        batch = np.ascontiguousarray(batch)
        with self._order_lock:
            t0 = time.perf_counter()
            self._seq += 1
            plan = self._plan(model_name, batch.shape)
            egress = 0
            phases: list[tuple[str, int, float]] = []
            for proc, ranges in plan.items():
                if proc == jax.process_index():
                    continue
                ts = time.perf_counter()
                payload = np.concatenate(
                    [batch[a:b] for a, b in ranges]).tobytes()
                self._feed.stage(self._seq, proc, payload)
                phases.append(("h2d", proc, time.perf_counter() - ts))
                egress += len(payload)
            self.last_egress_bytes = egress
            self.total_egress_bytes += egress
            self._broadcast_descriptor(
                self._model_index(model_name), self._seq, batch)
            ts = time.perf_counter()
            garr = self._assemble(model_name, batch.shape, batch.dtype,
                                  lambda a, b: batch[a:b])
            phases.append(("h2d", jax.process_index(),
                           time.perf_counter() - ts))
            self.last_ingest_s = time.perf_counter() - t0
            ts = time.perf_counter()
            try:
                out = self.runtime.run_batch(model_name, garr)
            finally:
                # The health gather must run even when the primary's own
                # execution raised: followers enter it unconditionally, and
                # a primary that skipped it would leave the slice's
                # collectives misaligned from here on.
                flags = self._gather_poison(0)
            # The jitted program is one SPMD execution across the slice;
            # its wall time is stamped under the primary's process index.
            phases.append(("execute", jax.process_index(),
                           time.perf_counter() - ts))
            with self._phases_lock:
                self._process_phases.extend(phases)
            if self.poison_listener is not None:
                self.poison_listener(list(flags))
            poisoned: set[int] = set()
            for proc, flag in enumerate(flags):
                if flag:
                    for a, b in plan.get(proc, []):
                        poisoned.update(range(a, b))
            return out, frozenset(poisoned)

    # -- ladder derivation (primary-gated, docs/mesh_serving.md) -------------

    @property
    def data_axis_size(self) -> int:
        return self.runtime.data_axis_size

    def prepare_buckets(self, name: str, buckets) -> tuple[int, ...]:
        """Warm-execute candidate ladder buckets THROUGH the broadcast
        path, so every follower enters (and jit-compiles) the same
        program — the deriver's dummy batches become ordinary SPMD
        executions instead of the primary-only calls the old
        ``build_worker`` refusal guarded against. Followers learn new
        bucket shapes from the descriptors themselves; the swap
        (``apply_ladder``) stays a primary-local attribute assignment
        because followers never cut batches — they only mirror shapes
        the primary broadcasts."""
        if jax.process_count() == 1:
            return self.runtime.prepare_buckets(name, buckets)
        from .sharding import pad_to_multiple
        servable = self.runtime.models[name]
        aligned = tuple(sorted({
            pad_to_multiple(int(b), self.data_axis_size) for b in buckets}))
        if not aligned:
            raise ValueError(f"empty ladder for {name}")
        for bucket in aligned:
            if (name, bucket) in self.runtime._executed_shapes:
                continue
            dummy = np.zeros((bucket, *servable.input_shape),
                             servable.input_dtype)
            # Marks (name, bucket) executed on every process via the
            # wrapped runtime's run_batch.
            self.run_batch_report(name, dummy)
        return aligned

    def apply_ladder(self, name: str, buckets) -> tuple[int, ...]:
        return self.runtime.apply_ladder(name, buckets)

    def drain_process_phases(self) -> list[tuple[str, int, float]]:
        """Pop the accumulated per-process device-phase tuples (the mesh
        endpoint forwards them into per-request hop ledgers)."""
        with self._phases_lock:
            out, self._process_phases = self._process_phases, []
        return out

    def shutdown_followers(self) -> None:
        if jax.process_count() > 1 and is_primary():
            with self._order_lock:
                self._broadcast_descriptor(_SHUTDOWN, 0, None)
                if self._feed is not None:
                    self._feed.shutdown()

    # -- follower side -------------------------------------------------------

    def follower_loop(self) -> None:
        """Run on every non-primary process: mirror the primary's batch
        executions until the shutdown sentinel arrives."""
        assert not is_primary(), "primary must not enter follower_loop"
        me = jax.process_index()
        while True:
            model_idx, seq, shape, dtype = self._receive_descriptor()
            if model_idx == _SHUTDOWN:
                log.info("follower %d: shutdown", me)
                return
            t0 = time.perf_counter()
            name = self._names[model_idx]
            ranges = self._plan(name, shape).get(me, [])
            offsets = {}
            at = 0
            for a, b in ranges:
                offsets[(a, b)] = at
                at += b - a
            poisoned = 0
            try:
                self._fetch_count += 1
                if self._fetch_count in _fault_fetch_nths():
                    # Fault injection (SURVEY.md §5 — the reference has
                    # none): AI4E_FAULT_FETCH_FAIL_NTHS="2,5" makes this
                    # follower's 2nd and 5th shard fetches fail, driving
                    # the zeros-shard + poison-report path in real
                    # multi-process tests.
                    raise RuntimeError(
                        f"injected fetch fault #{self._fetch_count}")
                raw = (_fetch(f"{self._feed_url}/shard/{seq}/{me}",
                              self._feed_token)
                       if ranges else b"")
                rows = np.frombuffer(raw, dtype).reshape(-1, *shape[1:])
                if at != rows.shape[0]:
                    raise RuntimeError(
                        f"feed sent {rows.shape[0]} rows, plan wants {at}")
            except Exception:  # noqa: BLE001 — a dead fetch must NOT desync
                # Every process must still enter the same compiled call or
                # the primary's next collective waits on a missing
                # participant and the whole slice deadlocks. Degrade to a
                # zeros shard — the slice lives — and report the poison on
                # the post-batch health gather so the primary FAILS this
                # follower's rows instead of serving zeros-scored results
                # (VERDICT r2 #5).
                log.exception(
                    "follower %d: shard fetch for %s seq %d failed; running "
                    "with a ZEROS shard to keep the slice in lockstep — "
                    "reporting these rows poisoned",
                    me, name, seq)
                rows = np.zeros((at, *shape[1:]), dtype)
                poisoned = 1

            def lookup(a, b):
                o = offsets[(a, b)]
                return rows[o:o + (b - a)]

            batch = self._assemble(name, shape, dtype, lookup)
            self.last_ingest_s = time.perf_counter() - t0
            try:
                self.runtime.run_batch(name, batch)
            except Exception:  # noqa: BLE001 — mirror the primary's policy
                # The primary catches the same device failure and keeps
                # serving (MicroBatcher._execute); a follower that died here
                # would leave the next broadcast waiting on a missing
                # participant and hang the whole slice. Its local rows are
                # garbage though — say so on the health gather.
                log.exception("follower %d: batch for %s failed; continuing",
                              me, name)
                poisoned = 1
            self._gather_poison(poisoned)

    # -- post-batch health gather -------------------------------------------

    def _gather_poison(self, my_flag: int) -> np.ndarray:
        """All-gather one int per process after every batch: 1 = this
        process's local rows are invalid (fetch degraded to zeros, or local
        execution failed). Costs one tiny DCN collective per batch — the
        price of never returning confidently wrong results. Returns the
        per-process flags, indexed by process id."""
        from jax.experimental import multihost_utils
        flags = multihost_utils.process_allgather(
            np.asarray([my_flag], np.int32))
        return np.asarray(flags).reshape(-1)

    # -- wire (descriptor: XLA collective; payload: shard feed) --------------

    def _broadcast_descriptor(self, model_idx: int, seq: int, batch) -> None:
        from jax.experimental import multihost_utils
        header = np.full((3 + _MAX_RANK,), 0, np.int32)
        header[0] = model_idx
        header[1] = seq
        if batch is not None:
            header[2] = _dtype_code(batch.dtype)
            rank = batch.ndim
            header[3:3 + rank] = batch.shape
        multihost_utils.broadcast_one_to_all(header)

    def _receive_descriptor(self):
        from jax.experimental import multihost_utils
        header = np.asarray(multihost_utils.broadcast_one_to_all(
            np.zeros((3 + _MAX_RANK,), np.int32)))
        model_idx = int(header[0])
        if model_idx == _SHUTDOWN:
            return model_idx, 0, None, None
        shape = tuple(int(d) for d in header[3:] if d > 0)
        return model_idx, int(header[1]), shape, _code_dtype(int(header[2]))


_DTYPES = [np.float32, np.float16, np.uint8, np.int32, np.int8]


def _dtype_code(dtype) -> int:
    for i, d in enumerate(_DTYPES):
        if np.dtype(dtype) == np.dtype(d):
            return i
    raise ValueError(f"unsupported broadcast dtype {dtype}")


def _code_dtype(code: int):
    return np.dtype(_DTYPES[code])
