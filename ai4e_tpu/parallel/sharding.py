"""Device mesh + sharding helpers — the framework's parallelism vocabulary.

The reference scales by replicating opaque GPU containers behind a queue
(SURVEY.md §2 parallelism inventory); here parallelism is first-class and
in-process: a named ``jax.sharding.Mesh`` over the TPU slice, with
``NamedSharding`` annotations and XLA-inserted collectives over ICI.

Axis conventions (scaling-book style):
- ``dp``   — data parallel: batch dimension sharded across replicas;
- ``fsdp`` — fully-sharded data parallel: parameters sharded on the same axis
  as data, all-gathered per layer;
- ``tp``   — tensor parallel: hidden/feature dimensions sharded; matmuls
  produce partial sums reduced with ``psum`` over ICI;
- ``sp``   — sequence parallel: long-context sequence dimension sharded (ring
  attention lives on this axis, see ``ring_attention.py``);
- ``ep``   — expert parallel: MoE experts sharded (reserved).

On a single host the mesh covers local devices; multi-host slices initialise
``jax.distributed`` first (``init_distributed``) and build the mesh over
``jax.devices()`` which then spans all hosts — the data plane the reference
never had (its NCCL-equivalent was HTTPS+queues, SURVEY.md §5).
"""

from __future__ import annotations

import dataclasses
import logging
import math
import os

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

log = logging.getLogger("ai4e_tpu.parallel")

AXES = ("dp", "fsdp", "tp", "sp", "ep")


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Logical mesh shape. Zero/one-sized axes are kept in the mesh (size 1)
    so PartitionSpecs referencing them always resolve."""

    dp: int = 1
    fsdp: int = 1
    tp: int = 1
    sp: int = 1
    ep: int = 1

    @property
    def size(self) -> int:
        return self.dp * self.fsdp * self.tp * self.sp * self.ep

    @classmethod
    def data_parallel(cls, n_devices: int) -> "MeshSpec":
        return cls(dp=n_devices)

    @classmethod
    def auto(cls, n_devices: int, model_parallel: int = 1,
             sequence_parallel: int = 1) -> "MeshSpec":
        """Fill dp with whatever model/sequence parallelism leaves over."""
        denom = model_parallel * sequence_parallel
        if n_devices % denom:
            raise ValueError(
                f"{n_devices} devices not divisible by tp*sp={denom}")
        return cls(dp=n_devices // denom, tp=model_parallel, sp=sequence_parallel)


def make_mesh(spec: MeshSpec | None = None,
              devices: list | None = None) -> Mesh:
    """Build the named mesh. Default: all local devices on ``dp``.

    Axis order places ``tp`` innermost so tensor-parallel collectives ride the
    fastest ICI links (nearest-neighbour on a v5e torus), with ``sp`` next —
    the layout guidance of the scaling-book recipe.
    """
    devices = devices if devices is not None else jax.devices()
    if spec is None:
        spec = MeshSpec.data_parallel(len(devices))
    if spec.size != len(devices):
        raise ValueError(f"mesh spec {spec} needs {spec.size} devices, "
                         f"got {len(devices)}")
    arr = np.array(devices).reshape(spec.dp, spec.fsdp, spec.ep, spec.sp, spec.tp)
    return Mesh(arr, ("dp", "fsdp", "ep", "sp", "tp"))


# -- sharding builders -----------------------------------------------------

def batch_sharding(mesh: Mesh, ndim: int = 2) -> NamedSharding:
    """Shard the leading (batch) dim over dp+fsdp, replicate the rest."""
    return NamedSharding(mesh, P(("dp", "fsdp"), *([None] * (ndim - 1))))

def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def spec_for_param(path: tuple, value, tp_rules=None) -> P:
    """PartitionSpec for one parameter by name-path match.

    Two rule forms, both first-match-wins on the ``/``-joined param path:

    - ``dict`` — substring → PartitionSpec (the original form; e.g.
      ``{"mlp/up": P(None, "tp")}``). No match: replicate.
    - ``list``/``tuple`` of ``(regex, PartitionSpec)`` pairs — the
      checkpoint-tree mapping the mesh serving plane declares
      (docs/mesh_serving.md#partition-rules): ``re.search`` per rule in
      order. Scalar (rank-0) leaves always replicate without consulting
      the rules; a non-scalar leaf NO rule matches raises ValueError at
      placement time — a regex rule set is a complete declaration, and a
      silently replicated tp param would serve wrong math on a split
      mesh, so the gap must fail registration, not the request path.
      End the list with ``(".*", P())`` to opt into replicate-by-default.

    This is the annotate-and-let-XLA-insert-collectives workflow: params
    get specs, pjit does the rest.
    """
    if isinstance(tp_rules, (list, tuple)):
        if not hasattr(value, "ndim") or value.ndim == 0:
            return P()
        import re
        joined = "/".join(str(p) for p in path)
        for pattern, spec in tp_rules:
            if re.search(pattern, joined):
                return spec
        raise ValueError(
            f"no partition rule matches param {joined!r} — regex rule sets "
            f"must be complete (add a ('.*', P()) catch-all to replicate)")
    if tp_rules:
        joined = "/".join(str(p) for p in path)
        for needle, spec in tp_rules.items():
            if needle in joined:
                return spec
    return P()


def shard_params(params, mesh: Mesh, tp_rules=None):
    """Place a pytree of params onto the mesh per ``tp_rules`` (either
    rule form ``spec_for_param`` accepts)."""
    flat, treedef = jax.tree_util.tree_flatten_with_path(params)
    placed = []
    for path, leaf in flat:
        spec = spec_for_param(tuple(p.key if hasattr(p, "key") else p.idx
                                    for p in path), leaf, tp_rules)
        placed.append(jax.device_put(leaf, NamedSharding(mesh, spec)))
    return jax.tree_util.tree_unflatten(treedef, placed)


# -- multi-host ------------------------------------------------------------

def init_distributed(coordinator: str | None = None,
                     num_processes: int | None = None,
                     process_id: int | None = None) -> None:
    """Initialise the cross-host data plane (``jax.distributed``) — the DCN
    layer under multi-host meshes. No-op when single-process.

    Reads JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES / JAX_PROCESS_ID when
    args are absent (typed-config-over-env, SURVEY.md §5 config system).
    """
    coordinator = coordinator or os.environ.get("JAX_COORDINATOR_ADDRESS")
    if not coordinator:
        return
    num_processes = num_processes or int(os.environ.get("JAX_NUM_PROCESSES", "1"))
    process_id = process_id if process_id is not None else int(
        os.environ.get("JAX_PROCESS_ID", "0"))
    if num_processes <= 1:
        return
    jax.distributed.initialize(coordinator, num_processes, process_id)
    log.info("jax.distributed up: %d processes, this is %d",
             num_processes, process_id)


def pad_to_multiple(n: int, multiple: int) -> int:
    return int(math.ceil(n / multiple) * multiple)
