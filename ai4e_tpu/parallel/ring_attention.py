"""Sequence/context parallelism: ring attention + Ulysses all-to-all.

Long-context serving is first-class in this framework (the reference has no
sequence dimension at all — SURVEY.md §5 long-context; this is the TPU-native
capability that slot gets). Two interchangeable strategies over the mesh's
``sp`` axis:

- **Ring attention** (``ring_attention``): K/V blocks rotate around the sp
  ring via ``jax.lax.ppermute`` while each device holds its Q shard; softmax
  is accumulated online (flash-attention style running max/denominator), so
  attention over a sequence of length S costs each device O(S·S/n) FLOPs and
  only ever materialises S/n-sized K/V blocks — communication rides
  nearest-neighbour ICI links and overlaps with the block matmuls.
- **Ulysses** (``ulysses_attention``): ``jax.lax.all_to_all`` reshuffles the
  sequence shard into a heads shard, runs ordinary full-sequence attention on
  1/n of the heads, and shuffles back. Cheaper at moderate S (two all-to-alls
  instead of n-1 permutes), but caps sp at the head count.

Both are pure SPMD collectives — XLA schedules them on ICI; no NCCL-style
backend exists or is needed (SURVEY.md §5 distributed-communication).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

try:  # jax >= 0.5 exports shard_map at the top level
    from jax import shard_map
except ImportError:  # 0.4.x keeps it under experimental
    from jax.experimental.shard_map import shard_map

if hasattr(jax.lax, "pcast"):
    _pcast = jax.lax.pcast
else:
    # 0.4.x shard_map has no varying-axis type system — device-constant
    # carries already unify with collective-produced values, so the cast
    # is the identity there.
    def _pcast(x, axes, to="varying"):
        del axes, to
        return x


def reference_attention(q, k, v, causal: bool = False):
    """Plain full attention — the correctness oracle for the parallel paths.

    Shapes: q (B, H, S, D), k/v (B, H, S, D).
    """
    scale = q.shape[-1] ** -0.5
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        s_q, s_k = scores.shape[-2], scores.shape[-1]
        mask = jnp.tril(jnp.ones((s_q, s_k), bool))
        scores = jnp.where(mask, scores, -jnp.inf)
    weights = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", weights, v)


def _ring_attention_local(q, k, v, axis_name: str, causal: bool,
                          vary_axes: tuple = ()):
    """Per-device body under shard_map: q/k/v are the local seq shards
    (B, H, S/n, D). ``vary_axes`` lists every manual axis the inputs vary
    over (the sp axis plus any batch axes) — the scan carry init must be
    marked varying over all of them to match the collective-produced carry."""
    n = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    s_local = q.shape[2]
    scale = q.shape[-1] ** -0.5

    q_pos = my_idx * s_local + jnp.arange(s_local)  # global positions of my Q

    def step(carry, t):
        o, m, l, k_blk, v_blk = carry
        # Which device's block do I currently hold? After t hops of a +1
        # rotation, block (my_idx - t) mod n.
        src = (my_idx - t) % n
        k_pos = src * s_local + jnp.arange(s_local)

        scores = jnp.einsum("bhqd,bhkd->bhqk", q, k_blk) * scale
        if causal:
            mask = q_pos[:, None] >= k_pos[None, :]
            scores = jnp.where(mask[None, None], scores, -jnp.inf)

        blk_max = jnp.max(scores, axis=-1, keepdims=True)
        m_new = jnp.maximum(m, blk_max)
        # All -inf rows (nothing visible yet in causal mode) → keep m to
        # avoid NaNs from (-inf) - (-inf).
        m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.exp(scores - m_safe)
        p = jnp.where(jnp.isfinite(scores), p, 0.0)
        correction = jnp.where(jnp.isfinite(m), jnp.exp(m - m_safe), 0.0)

        l_new = l * correction + jnp.sum(p, axis=-1, keepdims=True)
        o_new = o * correction + jnp.einsum("bhqk,bhkd->bhqd",
                                            p.astype(v_blk.dtype), v_blk)

        # Rotate K/V one hop around the ring (device i → i+1).
        perm = [(i, (i + 1) % n) for i in range(n)]
        k_next = jax.lax.ppermute(k_blk, axis_name, perm)
        v_next = jax.lax.ppermute(v_blk, axis_name, perm)
        return (o_new, m_new, l_new, k_next, v_next), None

    o0 = jnp.zeros_like(q)
    # Mark device-constant initial carries as axis-varying so the scan carry
    # type matches its (collective-produced, varying) outputs.
    vary = vary_axes or (axis_name,)
    m0 = _pcast(jnp.full((*q.shape[:3], 1), -jnp.inf, q.dtype), vary,
                to="varying")
    l0 = _pcast(jnp.zeros((*q.shape[:3], 1), q.dtype), vary,
                to="varying")
    (o, m, l, _, _), _ = jax.lax.scan(
        step, (o0, m0, l0, k, v), jnp.arange(n))
    return o / jnp.maximum(l, 1e-30)


def ring_attention(q, k, v, mesh: Mesh, causal: bool = False,
                   axis_name: str = "sp", batch_axes=None):
    """Sequence-parallel attention: inputs sharded (B, H, S@sp, D) on
    ``mesh``; output sharded the same way. ``batch_axes`` names mesh axes the
    batch dim is already sharded over (e.g. ``("dp", "fsdp")`` inside the
    serving runtime) so entering the shard_map doesn't force a gather."""
    spec = P(batch_axes, None, axis_name, None)
    if batch_axes is None:
        vary = (axis_name,)
    elif isinstance(batch_axes, str):
        vary = (batch_axes, axis_name)
    else:
        vary = (*batch_axes, axis_name)
    fn = shard_map(
        partial(_ring_attention_local, axis_name=axis_name, causal=causal,
                vary_axes=vary),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    return fn(q, k, v)


def _ulysses_local(q, k, v, axis_name: str, causal: bool):
    """Per-device: (B, H, S/n, D) → all-to-all → (B, H/n, S, D) → attention →
    back. Requires H % n == 0."""
    n = jax.lax.psum(1, axis_name)
    # Scatter heads (axis 1), gather sequence (axis 2).
    q2 = jax.lax.all_to_all(q, axis_name, split_axis=1, concat_axis=2,
                            tiled=True)
    k2 = jax.lax.all_to_all(k, axis_name, split_axis=1, concat_axis=2,
                            tiled=True)
    v2 = jax.lax.all_to_all(v, axis_name, split_axis=1, concat_axis=2,
                            tiled=True)
    o2 = reference_attention(q2, k2, v2, causal=causal)
    # Scatter sequence back, gather heads.
    return jax.lax.all_to_all(o2, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)


def ulysses_attention(q, k, v, mesh: Mesh, causal: bool = False,
                      axis_name: str = "sp", batch_axes=None):
    """All-to-all sequence parallelism (DeepSpeed-Ulysses style)."""
    n = mesh.shape[axis_name]
    if q.shape[1] % n:
        raise ValueError(f"heads {q.shape[1]} not divisible by sp={n}")
    spec = P(batch_axes, None, axis_name, None)
    fn = shard_map(
        partial(_ulysses_local, axis_name=axis_name, causal=causal),
        mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec)
    return fn(q, k, v)
