"""APIService — the in-container service shell.

Re-design of the reference's Flask ``APIService``
(``APIs/1.0/base-py/ai4e_service.py:44-213``) as an asyncio-native aiohttp app.
Same semantics, different engine:

- decorator-driven endpoint registration: ``api_sync_func`` / ``api_async_func``
  (``ai4e_service.py:72-109``) with per-endpoint concurrency caps,
  content-type and max-length limits, and a request-processing hook;
- backpressure: a request over the endpoint's cap gets **503** so the broker
  backs off and redelivers (``ai4e_service.py:116-133`` — the reference returns
  503; our dispatcher treats 503 and 429 identically);
- async endpoints create/adopt a task (reusing the ``taskId`` header when the
  dispatcher already created it), kick the user function onto a worker, and
  return the task id immediately (``ai4e_service.py:169-183``);
- any user-function exception fails the task (``ai4e_service.py:185-211``);
- graceful draining: SIGINT/SIGTERM flips ``is_terminating`` and all new
  requests get 503 while in-flight work finishes (``ai4e_service.py:111-120``);
- health check at ``GET {prefix}/`` and task polling at
  ``GET {prefix}/task/{id}`` (``ai4e_service.py:59-70``);
- ``GET /metrics`` Prometheus endpoint (replaces the RequestReporter POST loop,
  ``ai4e_service.py:135-156``).

Sync user functions run in a thread-pool executor; async (coroutine) user
functions run on the event loop. On a TPU host the executor is where JAX
dispatch happens — the event loop never blocks on device work.
"""

from __future__ import annotations

import asyncio
import logging
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass
from typing import Any, Callable

from aiohttp import web

from ..metrics import DEFAULT_REGISTRY, MetricsRegistry
from ..taskstore import InMemoryTaskStore, TaskStatus
from .task_manager import LocalTaskManager, TaskManagerBase

log = logging.getLogger("ai4e_tpu.service")

TASK_ID_HEADER = "taskId"  # set by the dispatcher (BackendQueueProcessor.cs:48-52)


@dataclass
class EndpointSpec:
    func: Callable
    api_path: str
    methods: tuple[str, ...]
    is_async: bool
    maximum_concurrent_requests: int = 8
    content_types: tuple[str, ...] = ()
    content_max_length: int = 0  # 0 = unlimited
    trace_name: str = ""
    request_processing_function: Callable | None = None
    # Extra admission predicate (no awaits): return (code, message) to refuse
    # the request, None to admit. Used e.g. to 503 when the TPU batcher is
    # saturated so the dispatcher backs off before a task is even adopted.
    admission_check: Callable | None = None
    # Mutated only from the event loop with no await between check and
    # increment — that single-threadedness is the synchronization.
    in_flight: int = 0


class APIService:
    def __init__(
        self,
        name: str,
        prefix: str = "",
        task_manager: TaskManagerBase | None = None,
        metrics: MetricsRegistry | None = None,
        executor_workers: int = 8,
        tracer=None,
        reporter=None,
    ):
        self.name = name
        self.prefix = ("/" + prefix.strip("/")) if prefix.strip("/") else ""
        if task_manager is None:
            task_manager = LocalTaskManager(InMemoryTaskStore())
        self.task_manager = task_manager
        self.metrics = metrics or DEFAULT_REGISTRY
        if tracer is None:
            from ..observability import Tracer
            # No explicit exporter/sample_rate → follows configure_tracer live.
            tracer = Tracer(name, metrics=self.metrics)
        self.tracer = tracer
        self.reporter = reporter  # ProcessingReporterClient | None
        self.is_terminating = False
        self.endpoints: dict[str, EndpointSpec] = {}
        self.executor = ThreadPoolExecutor(max_workers=executor_workers,
                                           thread_name_prefix=f"{name}-worker")
        self._background: set[asyncio.Task] = set()

        self._inflight = self.metrics.gauge(
            "ai4e_inflight_requests", "In-flight requests per endpoint")
        self._latency = self.metrics.histogram(
            "ai4e_request_latency_seconds", "End-to-end endpoint latency")
        self._http_total = self.metrics.counter(
            "ai4e_http_requests_total", "HTTP responses by code")

        self.app = web.Application(client_max_size=1024**3)
        self.app.router.add_get(self.prefix + "/", self._health)
        if self.prefix:
            self.app.router.add_get(self.prefix, self._health)
        self.app.router.add_get(self.prefix + "/task/{task_id}", self._task_status)
        self.app.router.add_get("/metrics", self._metrics_endpoint)

    # -- decorators (ai4e_service.py:103-109) ------------------------------

    def api_async_func(self, api_path: str, methods=("POST",), **kw):
        return self._api_func(api_path, methods, is_async=True, **kw)

    def api_sync_func(self, api_path: str, methods=("POST",), **kw):
        return self._api_func(api_path, methods, is_async=False, **kw)

    def _api_func(self, api_path: str, methods, is_async: bool,
                  maximum_concurrent_requests: int = 8,
                  content_types=(), content_max_length: int = 0,
                  trace_name: str = "", request_processing_function=None,
                  admission_check=None):
        def deco(func):
            spec = EndpointSpec(
                func=func,
                api_path=api_path if api_path.startswith("/") else "/" + api_path,
                methods=tuple(m.upper() for m in methods),
                is_async=is_async,
                maximum_concurrent_requests=maximum_concurrent_requests,
                content_types=tuple(content_types),
                content_max_length=content_max_length,
                trace_name=trace_name or api_path,
                request_processing_function=request_processing_function,
                admission_check=admission_check,
            )
            self.endpoints[spec.api_path] = spec
            route_path = self.prefix + spec.api_path
            for method in spec.methods:
                self.app.router.add_route(method, route_path,
                                          self._make_handler(spec))
            return func
        return deco

    # -- request admission (ai4e_service.py:116-133) -----------------------

    def _admission_error(self, spec: EndpointSpec, request: web.Request):
        """A refusal is ``(code, message)`` or ``(code, message, headers)``
        — the 3-tuple form lets admission checks attach refusal markers
        (``Retry-After``, ``X-Draining``) the caller's retry policy keys
        on (AIL015: every 429/503 must tell the caller when to retry)."""
        if self.is_terminating:
            return (503, "Service is shutting down.",
                    {"Retry-After": "1", "X-Draining": "1"})
        if spec.in_flight >= spec.maximum_concurrent_requests:
            return 503, "Too many requests; try again later.", {
                "Retry-After": "1"}
        if spec.content_types:
            ctype = request.content_type or ""
            if ctype not in spec.content_types:
                return 401, f"Unsupported content type: {ctype}"
        if spec.content_max_length and (request.content_length or 0) > spec.content_max_length:
            return 413, "Payload too large."
        if spec.admission_check is not None:
            refusal = spec.admission_check()
            if refusal is not None:
                return refusal
        return None

    def _reserve(self, spec: EndpointSpec) -> None:
        spec.in_flight += 1
        self._inflight.inc(path=spec.api_path, service=self.name)
        if self.reporter is not None:
            # Cross-replica aggregated counter (ai4e_service.py:148-151 POSTs
            # the same delta to REQUEST_REPORTER_URI); fire-and-forget.
            self.reporter.report(self.prefix + spec.api_path, increment=1)

    def _release(self, spec: EndpointSpec) -> None:
        spec.in_flight -= 1
        self._inflight.dec(path=spec.api_path, service=self.name)
        if self.reporter is not None:
            self.reporter.report(self.prefix + spec.api_path, decrement=1)

    def _make_handler(self, spec: EndpointSpec):
        async def handler(request: web.Request) -> web.Response:
            # Admission check + slot reservation happen with no await in
            # between, so the per-endpoint cap holds under concurrency (the
            # check would otherwise race across handlers suspended in
            # request.read()).
            err = self._admission_error(spec, request)
            if err:
                code, msg, *rest = err
                self._http_total.inc(code=str(code), path=spec.api_path)
                return web.Response(status=code, text=msg,
                                    headers=rest[0] if rest else None)
            self._reserve(spec)

            released_to_background = False
            try:
                if spec.request_processing_function is not None:
                    kwargs = spec.request_processing_function(request)
                    if asyncio.iscoroutine(kwargs):
                        kwargs = await kwargs
                    if kwargs is None:
                        self._http_total.inc(code="400", path=spec.api_path)
                        return web.Response(
                            status=400, text="Unable to process request data.")
                else:
                    kwargs = {"body": await request.read(),
                              "content_type": request.content_type}

                if spec.is_async:
                    resp = await self._run_async(spec, request, kwargs)
                    released_to_background = True  # _execute_async releases
                    return resp
                return await self._run_sync(spec, request, kwargs)
            finally:
                if not released_to_background:
                    self._release(spec)

        return handler

    # -- sync path (ai4e_service.py:158-167, 197-213) ----------------------

    async def _run_sync(self, spec: EndpointSpec, request: web.Request,
                        kwargs: dict) -> web.Response:
        t0 = time.perf_counter()
        try:
            # Span per endpoint execution (ai4e_service.py:158-167 wraps the
            # sync path in tracer.span); inbound x-b3 headers parent it.
            with self.tracer.span(spec.trace_name, headers=request.headers,
                                  path=spec.api_path):
                result = await self._invoke(spec.func, **kwargs)
            resp = self._to_response(result)
            self._http_total.inc(code=str(resp.status), path=spec.api_path)
            return resp
        except Exception as exc:  # noqa: BLE001
            log.exception("sync endpoint %s failed", spec.api_path)
            self._http_total.inc(code="500", path=spec.api_path)
            return web.Response(status=500, text=f"Error: {exc}")
        finally:
            self._latency.observe(time.perf_counter() - t0, path=spec.api_path)

    # -- async path (ai4e_service.py:169-213) ------------------------------

    async def _run_async(self, spec: EndpointSpec, request: web.Request,
                         kwargs: dict) -> web.Response:
        incoming_task_id = request.headers.get(TASK_ID_HEADER, "") or None
        endpoint = str(request.url)
        task = await self.task_manager.add_task(
            endpoint=endpoint, body=b"", task_id=incoming_task_id)
        task_id = task["TaskId"]
        if (incoming_task_id is not None
                and TaskStatus.canonical(task.get("Status", ""))
                in TaskStatus.TERMINAL):
            # Terminal re-check at adoption (AIL003): a redelivered message
            # for a task that already finished (lease-expiry redelivery
            # racing a completion, a duplicated publish, a retried delivery
            # whose first response was lost) must not re-execute — the
            # handler's running/completed writes would clobber the terminal
            # status the client may already have read, and the client would
            # observe a second completion. 200 acks the message; the work is
            # done. Re-executions the platform MEANS to happen (reaper
            # requeue, pipeline handoff) rewrite the task to `created`
            # before republishing, so they pass this check.
            self._release(spec)
            self._http_total.inc(code="200", path=spec.api_path)
            return web.json_response(task)

        # The reserved slot is held until the background execution finishes —
        # the cap covers running tasks, not just open connections
        # (ai4e_service.py:197-213 counts the worker thread the same way).
        from ..observability import PARENT_HEADER, SAMPLED_HEADER, SPAN_HEADER, TRACE_HEADER
        parent_headers = {
            k: request.headers[k]
            for k in (TRACE_HEADER, SPAN_HEADER, PARENT_HEADER, SAMPLED_HEADER)
            if k in request.headers
        }
        bg = asyncio.get_running_loop().create_task(
            self._execute_async(spec, task_id, kwargs, parent_headers))
        self._background.add(bg)
        bg.add_done_callback(self._background.discard)

        self._http_total.inc(code="200", path=spec.api_path)
        return web.json_response({"TaskId": task_id, "Status": task.get("Status", "created")})

    async def _execute_async(self, spec: EndpointSpec, task_id: str,
                             kwargs: dict,
                             parent_headers: dict | None = None) -> None:
        t0 = time.perf_counter()
        try:
            # The span keyed by TaskId covers the whole background execution
            # (the worker-thread hot loop, ai4e_service.py:169-183).
            with self.tracer.span(spec.trace_name, task_id=task_id,
                                  headers=parent_headers, path=spec.api_path):
                await self._invoke(spec.func, taskId=task_id, **kwargs)
        except Exception as exc:  # noqa: BLE001
            log.exception("async endpoint %s task %s failed", spec.api_path, task_id)
            try:
                # Terminal re-check (AIL003): a handler that completed the
                # task and THEN raised (cleanup error after complete_task)
                # must not flip the completion the client may already have
                # read to `failed`.
                if not await self.task_manager.is_terminal(task_id):
                    await self.task_manager.fail_task(task_id, f"failed: {exc}")
            except Exception:  # noqa: BLE001
                log.exception("could not fail task %s", task_id)
        finally:
            self._release(spec)
            self._latency.observe(time.perf_counter() - t0, path=spec.api_path)

    async def _invoke(self, func: Callable, **kwargs) -> Any:
        if asyncio.iscoroutinefunction(func):
            return await func(**kwargs)
        loop = asyncio.get_running_loop()
        return await loop.run_in_executor(self.executor, lambda: func(**kwargs))

    @staticmethod
    def _to_response(result: Any) -> web.Response:
        if isinstance(result, web.Response):
            return result
        if isinstance(result, (dict, list)):
            return web.json_response(result)
        if isinstance(result, bytes):
            return web.Response(body=result)
        return web.Response(text=str(result))

    # -- built-in routes ---------------------------------------------------

    async def _health(self, _: web.Request) -> web.Response:
        if self.is_terminating:
            return web.Response(status=503, text="Draining.",
                                headers={"Retry-After": "1",
                                         "X-Draining": "1"})
        return web.json_response({"service": self.name, "status": "healthy"})

    async def _task_status(self, request: web.Request) -> web.Response:
        status = await self.task_manager.get_task_status(
            request.match_info["task_id"])
        if status is None:
            return web.Response(status=404, text="Task not found.")
        return web.json_response(status)

    async def _metrics_endpoint(self, _: web.Request) -> web.Response:
        return web.Response(text=self.metrics.render_prometheus(),
                            content_type="text/plain")

    # -- lifecycle ---------------------------------------------------------

    def begin_draining(self) -> None:
        log.warning("draining: refusing new requests")
        self.is_terminating = True

    async def drain(self, timeout: float = 30.0) -> None:
        """Refuse new work, then wait for in-flight async tasks — the drain
        window the reference gets from is_terminating + worker threads
        (ai4e_service.py:111-120)."""
        self.is_terminating = True
        if self._background:
            await asyncio.wait(self._background, timeout=timeout)

    def run(self, host: str = "0.0.0.0", port: int = 8081,
            drain_timeout: float = 30.0) -> None:
        """Serve until SIGINT/SIGTERM; aiohttp's runner owns the signal →
        shutdown path, and our on_shutdown hook drains in-flight tasks before
        the process exits."""

        async def _on_shutdown(_app):
            await self.drain(drain_timeout)

        self.app.on_shutdown.append(_on_shutdown)
        web.run_app(self.app, host=host, port=port,
                    shutdown_timeout=drain_timeout)
