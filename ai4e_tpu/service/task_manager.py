"""Task-manager facade used inside API services.

Same contract as the reference's two-layer manager — the ``TaskManager`` facade
(``APIs/1.0/base-py/task_management/api_task.py:8-38``) over
``DistributedApiTaskManager`` (``APIs/1.0/Common/task_management/
distributed_api_task.py:17-116``) — with two interchangeable backends:

- ``LocalTaskManager``  — direct calls into an in-process ``InMemoryTaskStore``
  (single-host deployments, tests);
- ``HttpTaskManager``   — aiohttp client against the task-store service
  (multi-host; the reference's CACHE_CONNECTOR_UPSERT_URI/GET_URI pattern,
  ``distributed_api_task.py:14-15``).

Both are async; sync user code goes through the service shell's executor.
"""

from __future__ import annotations

import asyncio
import json
from urllib.parse import urlparse

import aiohttp

from ..taskstore import (APITask, InMemoryTaskStore, NotPrimaryError,
                         TaskNotFound, TaskStatus)
from ..utils.http import SessionHolder


class StoreRefusalError(NotPrimaryError):
    """A typed store refusal a caller must not mistake for generic
    failure: carries the refusing status and the store's Retry-After.
    ``NotPrimaryError`` subclass so the service shell's standby mapping
    (gateway answers 503 + Retry-After, client retries) applies — a
    refused write is a backpressure signal, not a 500."""

    def __init__(self, message: str, *, status: int,
                 retry_after: str | None = None):
        super().__init__(message)
        self.status = status
        self.retry_after = retry_after


def _raise_refusal(resp) -> None:
    """Distinguish the store's typed refusals BEFORE any generic
    ``raise_for_status``: a plain 503 is the store refusing load
    (journal-degraded / draining / overloaded — ``_request`` already
    rotated the X-Not-Primary flavor), and a 409 carrying X-Not-Owner is
    the hash-ring fence (this writer raced a rebalance handoff). A bare
    409 (conditional-update precondition) passes through — that one IS
    the caller's branch to take."""
    if resp.status == 503:
        reason = resp.headers.get("X-Shed-Reason") or "store unavailable"
        raise StoreRefusalError(f"store refused: {reason}", status=503,
                                retry_after=resp.headers.get("Retry-After"))
    if resp.status == 409 and resp.headers.get("X-Not-Owner"):
        raise StoreRefusalError(
            "store is no longer the shard owner for this task", status=409)


class TaskManagerBase:
    """AddTask / UpdateTaskStatus / CompleteTask / FailTask / AddPipelineTask /
    GetTaskStatus — the five verbs every AI4E service uses."""

    async def add_task(self, endpoint: str, body: bytes, task_id: str | None = None,
                       publish: bool = False) -> dict:
        """Create a task — or, when ``task_id`` is supplied (the dispatcher
        already created it and passed the ``taskId`` header), just fetch it
        (``api_task.py:12-20``)."""
        if task_id:
            status = await self.get_task_status(task_id)
            if status is not None:
                return status
        return await self._upsert(APITask(
            task_id=task_id or "", endpoint=endpoint, body=body, publish=publish,
        ))

    async def update_task_status(self, task_id: str, status: str,
                                 backend_status: str | None = None) -> dict:
        return await self._update(task_id, status, backend_status)

    async def update_task_status_if(self, task_id: str,
                                    expected_status: str, status: str,
                                    backend_status: str | None = None
                                    ) -> dict | None:
        """Conditional transition: apply iff the task's canonical status is
        still ``expected_status``; None when the precondition failed (a
        concurrent path already transitioned it — the caller's write is a
        duplicate and must not land). This is the remote-store-safe form
        of the terminal-clobber guard (docs/concurrency.md): the condition
        is evaluated under the store's lock, not across a network hop."""
        raise NotImplementedError

    async def complete_task(self, task_id: str, status: str = "completed") -> dict:
        return await self._update(task_id, status, TaskStatus.COMPLETED)

    async def fail_task(self, task_id: str, status: str = "failed") -> dict:
        return await self._update(task_id, status, TaskStatus.FAILED)

    async def add_pipeline_task(self, task_id: str, next_endpoint: str,
                                body: bytes = b"") -> dict:
        """Hand the task to the next API in an ensemble: rewrite Endpoint,
        republish; an empty body triggers original-body replay downstream
        (``distributed_api_task.py:67-100``)."""
        return await self._upsert(APITask(
            task_id=task_id,
            endpoint=next_endpoint,
            body=body,
            status=TaskStatus.CREATED,
            backend_status=TaskStatus.CREATED,
            publish=True,
        ))

    async def get_task_status(self, task_id: str) -> dict | None:
        raise NotImplementedError

    async def append_ledger(self, task_id: str, events: list[dict]) -> int:
        """Append hop-ledger events to the task's timeline on the store
        (observability/ledger.py). Base default is a no-op so duck-typed
        task-manager substitutes keep working; the real backends
        forward to ``InMemoryTaskStore.append_ledger`` directly or over
        ``POST /v1/taskstore/ledger``. Callers treat failures as
        droppable — the ledger is fail-open telemetry."""
        return 0

    async def is_terminal(self, task_id: str) -> bool:
        """Terminal-status probe — the shared guard for status-writing cold
        paths (AIL003; the dispatcher, webhook, and service shell all use
        it before writes that could clobber a completed task on a
        redelivery). A failed probe answers False — the caller must not
        stall on a store hiccup — and is logged so a store outage
        degrading duplicate suppression is visible."""
        import logging
        try:
            record = await self.get_task_status(task_id)
        except Exception:  # noqa: BLE001 — a probe must never block its caller
            logging.getLogger("ai4e_tpu.task_manager").warning(
                "status probe for task %s failed; proceeding as "
                "non-terminal", task_id, exc_info=True)
            return False
        if not record:
            return False
        return TaskStatus.canonical(
            record.get("Status", "")) in TaskStatus.TERMINAL

    async def _upsert(self, task: APITask) -> dict:
        raise NotImplementedError

    async def _update(self, task_id: str, status: str,
                      backend_status: str | None = None) -> dict:
        raise NotImplementedError


class LocalTaskManager(TaskManagerBase):
    def __init__(self, store: InMemoryTaskStore):
        self.store = store

    async def get_task_status(self, task_id: str) -> dict | None:
        try:
            return self.store.get(task_id).to_dict()
        except TaskNotFound:
            return None

    async def _upsert(self, task: APITask) -> dict:
        # Distinguish create vs. pipeline transition the way the store does.
        return self.store.upsert(task).to_dict()

    async def _update(self, task_id: str, status: str,
                      backend_status: str | None = None) -> dict:
        return self.store.update_status(task_id, status, backend_status).to_dict()

    async def update_task_status_if(self, task_id: str,
                                    expected_status: str, status: str,
                                    backend_status: str | None = None
                                    ) -> dict | None:
        task = self.store.update_status_if(task_id, expected_status, status,
                                           backend_status)
        return None if task is None else task.to_dict()

    async def append_ledger(self, task_id: str, events: list[dict]) -> int:
        append = getattr(self.store, "append_ledger", None)
        if append is None:  # duck-typed store substitutes in tests
            return 0
        return append(task_id, events)


class _HttpStoreClient:
    """Shared plumbing for clients of the task-store HTTP service.

    ``base_url`` may be a single URL or a list — the control-plane replica
    set (primary first; ``deploy/charts/control-plane-standby.yaml``). On a
    connection failure or a 503 "not primary" the client rotates to the
    next replica and retries, sticking with whichever answered (the role
    the reference's RedisConnection retry policy + managed failover played,
    ``RedisConnection.cs:18-19``). ``api_key`` rides as a default
    ``Ocp-Apim-Subscription-Key`` header on every request — required when
    the control plane runs with gateway subscription keys (the task-store
    surface on that port is keyed too; set
    ``AI4E_SERVICE_TASKSTORE_API_KEY`` on workers). Ignored when the
    caller passes its own ``session``.
    """

    def __init__(self, base_url: str | list[str],
                 session: aiohttp.ClientSession | None = None,
                 api_key: str | None = None,
                 failover_cycles: int = 10, failover_delay: float = 1.0):
        """``failover_cycles``/``failover_delay`` size the replica-set
        patience: with a list, a request gives the pair
        ``cycles × delay`` (~9 s at the defaults) before surfacing an
        error. It must COVER the watchdog's promotion window (default
        detection alone is ``failover_down_after × failover_interval``
        = 6 s) — the live failover drive measured tasks whose inference
        SUCCEEDED being FailTask'd because a ~1.5 s patience expired
        inside a ~2 s promotion (scripts/ha_failover_drive.py; 6 of 18k
        tasks at even an aggressive 0.5 s watchdog). Giving up early
        converts a transient window into a permanent task failure, so
        patience errs long; single-endpoint deployments skip all of
        this (no cycles, no delay)."""
        urls = [base_url] if isinstance(base_url, str) else list(base_url)
        if not urls:
            raise ValueError("at least one task-store URL is required")
        self._endpoints = [u.rstrip("/") for u in urls]
        self.base_url = self._endpoints[0]
        self._failover_cycles = failover_cycles
        self._failover_delay = failover_delay
        headers = ({"Ocp-Apim-Subscription-Key": api_key}
                   if api_key else None)
        self._holder = SessionHolder(session, headers=headers)
        # Highest fencing epoch any replica has shown us (X-Store-Epoch).
        # Echoed on every request: a client that has talked to the new
        # primary carries the evidence that demotes a stale one
        # (taskstore/replication.py module docs).
        self.store_epoch = 0

    async def _get_session(self) -> aiohttp.ClientSession:
        return await self._holder.get()

    async def _request(self, method: str, path: str, **kwargs
                       ) -> tuple[aiohttp.ClientResponse, bytes]:
        """One store round trip with replica failover: try the active
        endpoint, rotate on connection errors / timeouts / 503-not-primary.
        With a single endpoint this is a plain request (no retry tax on the
        common deployment). Returns ``(response, body)`` — the body is read
        inside the request context (aiohttp refuses reads on a released
        response) and the response object carries status/headers."""
        session = await self._get_session()
        last_exc: Exception | None = None
        single = len(self._endpoints) == 1
        cycles = 1 if single else self._failover_cycles
        for cycle in range(cycles):
            ordered = ([self.base_url]
                       + [e for e in self._endpoints if e != self.base_url])
            for base in ordered:
                try:
                    if self.store_epoch:
                        headers = dict(kwargs.pop("headers", None) or {})
                        headers.setdefault("X-Store-Epoch",
                                           str(self.store_epoch))
                        kwargs["headers"] = headers
                    async with session.request(
                            method, base + path, **kwargs) as resp:
                        body = await resp.read()
                    seen = resp.headers.get("X-Store-Epoch")
                    if seen and seen.isdigit():
                        self.store_epoch = max(self.store_epoch, int(seen))
                    if (resp.status == 503 and not single
                            and resp.headers.get("X-Not-Primary")):
                        # A follower replica refusing the write — rotate.
                        # A PLAIN 503 (overloaded/draining primary) is
                        # returned to the caller: rotating on it would
                        # stick reads to a lagging follower (ADVICE r4).
                        last_exc = aiohttp.ClientResponseError(
                            resp.request_info, (), status=503,
                            message="replica not primary")
                        continue
                    self.base_url = base
                    return resp, body
                except (aiohttp.ClientConnectionError,
                        asyncio.TimeoutError, OSError) as exc:
                    last_exc = exc
                    continue
            if cycle + 1 < cycles:
                # Every replica refused/unreachable: failover may be mid
                # promotion (watchdog needs a few probe intervals) — wait
                # one beat and re-cycle before giving up.
                await asyncio.sleep(self._failover_delay)
        assert last_exc is not None
        raise last_exc

    async def close(self) -> None:
        await self._holder.close()


class HttpTaskManager(_HttpStoreClient, TaskManagerBase):
    """Client for the task-store HTTP service (``taskstore.http``)."""

    async def get_task_status(self, task_id: str) -> dict | None:
        resp, body = await self._request("GET", "/v1/taskstore/task",
                                         params={"taskId": task_id})
        if resp.status != 200:
            return None
        return json.loads(body)

    async def _upsert(self, task: APITask) -> dict:
        payload = task.to_dict()
        payload["Body"] = task.body.decode("utf-8", errors="surrogateescape")
        payload["PublishToGrid"] = task.publish
        resp, body = await self._request("POST", "/v1/taskstore/upsert",
                                         data=json.dumps(payload))
        _raise_refusal(resp)
        resp.raise_for_status()
        return json.loads(body)

    async def _update(self, task_id: str, status: str,
                      backend_status: str | None = None) -> dict:
        # Atomic server-side transition — no GET-then-POST race
        # (unlike the reference's _UpdateTaskStatus, distributed_api_task.py:29-56).
        payload = {
            "TaskId": task_id,
            "Status": status,
            "BackendStatus": backend_status or TaskStatus.canonical(status),
        }
        resp, body = await self._request("POST", "/v1/taskstore/update",
                                         data=json.dumps(payload))
        _raise_refusal(resp)
        resp.raise_for_status()
        if resp.status != 200:  # 204 = task unknown to the store
            raise KeyError(f"task not found: {task_id}")
        return json.loads(body)

    async def update_task_status_if(self, task_id: str,
                                    expected_status: str, status: str,
                                    backend_status: str | None = None
                                    ) -> dict | None:
        """Conditional wire transition — ``ExpectedStatus`` evaluates under
        the STORE's lock (``POST /v1/taskstore/update``), closing the
        probe-then-write residual window a remote writer otherwise carries
        (docs/concurrency.md). 409 (precondition failed) and 204 (task
        unknown/evicted) both answer None: either way this writer's
        transition must not land."""
        payload = {
            "TaskId": task_id,
            "Status": status,
            "BackendStatus": backend_status or TaskStatus.canonical(status),
            "ExpectedStatus": expected_status,
        }
        resp, body = await self._request("POST", "/v1/taskstore/update",
                                         data=json.dumps(payload))
        _raise_refusal(resp)  # fence-409 is NOT the precondition branch
        if resp.status in (409, 204):
            return None
        resp.raise_for_status()
        return json.loads(body)

    async def append_ledger(self, task_id: str, events: list[dict]) -> int:
        """Ship the worker's buffered hop-ledger events to the control
        plane in one POST — the cross-process leg of the per-task
        timeline (observability/ledger.py). A store without the surface
        (older control plane) answers 404/405: treated as zero appended,
        never an error — the ledger is fail-open telemetry."""
        payload = {"TaskId": task_id, "Events": events}
        resp, body = await self._request("POST", "/v1/taskstore/ledger",
                                         data=json.dumps(payload))
        if resp.status in (409, 503):
            # Typed refusal (ring fence / degraded journal): the stamp is
            # dropped like any other miss — deliberately, the ledger never
            # blocks serving — but not mistaken for a missing surface.
            return 0
        if resp.status != 200:
            return 0
        try:
            return int(json.loads(body).get("appended", 0))
        except (json.JSONDecodeError, ValueError, AttributeError):
            return 0


class HttpResultStore(_HttpStoreClient):
    """Result read/write against the task-store HTTP service — gives remote
    workers the same ``set_result``/``get_result`` surface the in-process
    store offers (methods are coroutines; the worker awaits either form)."""

    async def set_result(self, task_id: str, result: bytes,
                         content_type: str = "application/json",
                         stage: str | None = None) -> None:
        params = {"taskId": task_id}
        if stage:
            params["stage"] = stage
        resp, _body = await self._request(
            "POST", "/v1/taskstore/result", params=params,
            data=result, headers={"Content-Type": content_type})
        _raise_refusal(resp)
        if resp.status == 404:
            # Store no longer knows the task (e.g. control plane
            # restarted without a journal) — surface the drop; the
            # subsequent complete_task will fail loudly too.
            import logging
            logging.getLogger("ai4e_tpu.task_manager").warning(
                "result for unknown task %s dropped by store", task_id)
            return
        resp.raise_for_status()

    async def set_result_ref(self, task_id: str,
                             content_type: str = "application/json",
                             stage: str | None = None) -> None:
        """Register a blob already written to the shared result backend
        (direct-to-storage workers) — tiny JSON instead of the payload."""
        payload = {"TaskId": task_id, "ContentType": content_type}
        if stage:
            payload["Stage"] = stage
        resp, _body = await self._request("POST", "/v1/taskstore/result-ref",
                                          data=json.dumps(payload))
        _raise_refusal(resp)
        if resp.status == 404:
            import logging
            logging.getLogger("ai4e_tpu.task_manager").warning(
                "result ref for unknown task %s dropped by store",
                task_id)
            return False  # caller may reap the orphaned blob
        resp.raise_for_status()
        return True

    async def get_result(self, task_id: str,
                         stage: str | None = None
                         ) -> tuple[bytes, str] | None:
        params = {"taskId": task_id}
        if stage:
            params["stage"] = stage
        resp, body = await self._request("GET", "/v1/taskstore/result",
                                         params=params)
        if resp.status != 200:
            return None
        return body, resp.content_type


class DirectResultStore:
    """Worker-side direct-to-storage results — the reference's
    blob-access slot (containers write outputs straight to storage,
    ``APIs/helpers/assign_storage_auth_to_aks.sh:9-17``): payloads at or
    over ``threshold`` bytes write to the SHARED result mount under the
    canonical key and only a pointer registration crosses the control
    network; smaller results fall through to the wrapped store. The mount
    must be the same root the control plane serves
    (``AI4E_PLATFORM_RESULT_DIR``) — a mis-mount surfaces as a 409 on
    registration, never as a dangling pointer."""

    def __init__(self, root: str, inner, threshold: int = 1024 * 1024):
        from ..taskstore.results import FileResultBackend

        self.backend = FileResultBackend(root)
        self.inner = inner
        self.threshold = threshold

    async def set_result(self, task_id: str, result: bytes,
                         content_type: str = "application/json",
                         stage: str | None = None) -> None:
        import asyncio
        import inspect

        if len(result) >= self.threshold:
            key = task_id if stage is None else f"{task_id}:{stage}"
            # Blob write off the event loop (shared mounts are slow I/O),
            # BEFORE the pointer registration.
            await asyncio.to_thread(self.backend.put, key, result,
                                    content_type)
            try:
                res = self.inner.set_result_ref(task_id, content_type,
                                                stage=stage)
                if inspect.isawaitable(res):
                    res = await res
            except Exception:
                # Registration failed: reap the just-written blob or it
                # leaks on the shared mount forever.
                await asyncio.to_thread(self.backend.delete, key)
                raise
            if res is False:  # store dropped the ref (unknown task)
                await asyncio.to_thread(self.backend.delete, key)
            return
        res = self.inner.set_result(task_id, result, content_type,
                                    stage=stage)
        if inspect.isawaitable(res):
            await res

    async def get_result(self, task_id: str, stage: str | None = None):
        import inspect

        res = self.inner.get_result(task_id, stage=stage)
        return await res if inspect.isawaitable(res) else res

    async def close(self) -> None:
        import inspect

        close = getattr(self.inner, "close", None)
        if close is not None:
            res = close()
            if inspect.isawaitable(res):
                await res


def next_endpoint_from(current_endpoint: str, version: str, organization: str,
                       api: str) -> str:
    """Build the next pipeline stage's endpoint from the current one —
    ``scheme://host/{version}/{org}/{api}`` (``distributed_api_task.py:74-75``)."""
    parsed = urlparse(current_endpoint)
    base = f"{parsed.scheme}://{parsed.netloc}" if parsed.scheme else ""
    return f"{base}/{version}/{organization}/{api}"
