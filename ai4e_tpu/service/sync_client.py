"""Synchronous task-manager client — for user model code on worker threads.

The reference ships two Python task-manager clients: the async/aiohttp one
(``APIs/1.0/Common/task_management/distributed_api_task.py``) and an older
synchronous ``requests``-based variant with the identical verb set
(``Containers/Common/task_management/distributed_api_task.py:12-86``). User
model functions run on worker threads (``ai4e_service.py:180-183`` spawns a
thread per async task), where a blocking client is the natural fit — awaiting
the async manager from a thread means bouncing through ``asyncio.run`` per
call.

``SyncTaskManager`` is that variant for the TPU platform: the same six verbs
(AddTask / UpdateTaskStatus / CompleteTask / FailTask / AddPipelineTask /
GetTaskStatus) plus result upload, blocking, stdlib-only (urllib — no
dependency on the event loop or on ``requests``), against the task-store HTTP
surface (``taskstore.http``).
"""

from __future__ import annotations

import json
import logging
import urllib.error
import urllib.parse
import urllib.request

from ..taskstore import TaskStatus

log = logging.getLogger("ai4e_tpu.sync_client")


class SyncTaskManager:
    """Blocking task CRUD against the task-store HTTP service.

    Mirrors ``TaskManagerBase``'s contract (which mirrors the reference's
    manager facade, ``api_task.py:8-38``) with plain methods instead of
    coroutines.
    """

    def __init__(self, base_url: str, timeout: float = 60.0):
        self.base_url = base_url.rstrip("/")
        self.timeout = timeout

    # -- wire helpers ------------------------------------------------------

    def _post(self, path: str, payload: dict | bytes,
              content_type: str = "application/json",
              query: dict | None = None) -> tuple[int, bytes]:
        url = f"{self.base_url}{path}"
        if query:
            url += "?" + urllib.parse.urlencode(query)
        data = (json.dumps(payload).encode()
                if isinstance(payload, dict) else payload)
        req = urllib.request.Request(
            url, data=data, method="POST",
            headers={"Content-Type": content_type})
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                return resp.status, resp.read()
        except urllib.error.HTTPError as exc:
            return exc.code, exc.read()

    def _get(self, path: str, query: dict) -> tuple[int, bytes]:
        url = f"{self.base_url}{path}?" + urllib.parse.urlencode(query)
        req = urllib.request.Request(url)
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                return resp.status, resp.read()
        except urllib.error.HTTPError as exc:
            return exc.code, exc.read()

    # -- the six verbs -----------------------------------------------------

    def add_task(self, endpoint: str, body: bytes = b"",
                 task_id: str | None = None, publish: bool = False) -> dict:
        """Create a task — or fetch it when the dispatcher already created it
        and passed the ``taskId`` header (``api_task.py:12-20``)."""
        if task_id:
            status = self.get_task_status(task_id)
            if status is not None:
                return status
        payload = {
            "TaskId": task_id or "",
            "Endpoint": endpoint,
            "Status": TaskStatus.CREATED,
            "BackendStatus": TaskStatus.CREATED,
            "Body": body.decode("utf-8", errors="surrogateescape"),
            "PublishToGrid": publish,
        }
        code, data = self._post("/v1/taskstore/upsert", payload)
        if code != 200:
            raise RuntimeError(f"upsert failed: HTTP {code}")
        return json.loads(data)

    def update_task_status(self, task_id: str, status: str,
                           backend_status: str | None = None) -> dict:
        payload = {"TaskId": task_id, "Status": status,
                   "BackendStatus": backend_status
                   or TaskStatus.canonical(status)}
        code, data = self._post("/v1/taskstore/update", payload)
        if code == 204:
            raise KeyError(f"task not found: {task_id}")
        if code != 200:
            raise RuntimeError(f"update failed: HTTP {code}")
        return json.loads(data)

    def complete_task(self, task_id: str, status: str = "completed") -> dict:
        return self.update_task_status(task_id, status, TaskStatus.COMPLETED)

    def fail_task(self, task_id: str, status: str = "failed") -> dict:
        return self.update_task_status(task_id, status, TaskStatus.FAILED)

    def add_pipeline_task(self, task_id: str, next_endpoint: str,
                          body: bytes = b"") -> dict:
        """Hand the task to the next API: rewrite Endpoint, republish; an
        empty body replays the original downstream
        (``distributed_api_task.py:67-100``)."""
        payload = {
            "TaskId": task_id,
            "Endpoint": next_endpoint,
            "Status": TaskStatus.CREATED,
            "BackendStatus": TaskStatus.CREATED,
            "Body": body.decode("utf-8", errors="surrogateescape"),
            "PublishToGrid": True,
        }
        code, data = self._post("/v1/taskstore/upsert", payload)
        if code != 200:
            raise RuntimeError(f"pipeline upsert failed: HTTP {code}")
        return json.loads(data)

    def get_task_status(self, task_id: str) -> dict | None:
        code, data = self._get("/v1/taskstore/task", {"taskId": task_id})
        if code != 200:
            return None
        return json.loads(data)

    # -- results -----------------------------------------------------------

    def set_result(self, task_id: str, result: bytes,
                   content_type: str = "application/json",
                   stage: str | None = None) -> None:
        query = {"taskId": task_id}
        if stage:
            query["stage"] = stage
        code, _ = self._post("/v1/taskstore/result", result,
                             content_type=content_type, query=query)
        if code == 404:
            log.warning("result for unknown task %s dropped by store", task_id)
            return
        if not (200 <= code < 300):
            raise RuntimeError(f"set_result failed: HTTP {code}")

    def get_result(self, task_id: str,
                   stage: str | None = None) -> bytes | None:
        query = {"taskId": task_id}
        if stage:
            query["stage"] = stage
        code, data = self._get("/v1/taskstore/result", query)
        return data if code == 200 else None
