from .app import APIService, EndpointSpec, TASK_ID_HEADER
from .sync_client import SyncTaskManager
from .task_manager import (
    HttpResultStore,
    HttpTaskManager,
    LocalTaskManager,
    TaskManagerBase,
    next_endpoint_from,
)

__all__ = [
    "APIService",
    "EndpointSpec",
    "TASK_ID_HEADER",
    "HttpResultStore",
    "HttpTaskManager",
    "LocalTaskManager",
    "SyncTaskManager",
    "TaskManagerBase",
    "next_endpoint_from",
]
