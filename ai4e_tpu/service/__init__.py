from .app import APIService, EndpointSpec, TASK_ID_HEADER
from .task_manager import (
    HttpTaskManager,
    LocalTaskManager,
    TaskManagerBase,
    next_endpoint_from,
)

__all__ = [
    "APIService",
    "EndpointSpec",
    "TASK_ID_HEADER",
    "HttpTaskManager",
    "LocalTaskManager",
    "TaskManagerBase",
    "next_endpoint_from",
]
