"""Checkpoint / resume — durable model state via Orbax.

The reference has **no** model or job checkpointing (SURVEY.md §5): its only
durable state is the task record in Redis — a crashed worker's message is
redelivered and any replica resumes the task by TaskId
(``ProcessManager/BackendQueueProcessor/host.json:7`` autoComplete:false,
``CacheConnectorUpsert.cs:158`` original-body persistence). Model weights live
frozen inside opaque containers.

The TPU build keeps that task-level durability (``taskstore.JournaledTaskStore``)
and adds the layer the reference couldn't have:

- **serving**: workers restore servable params from a checkpoint at pod start
  (``load_params`` with the model's init tree) instead of baking weights into
  images — the
  model-distribution slot the reference fills with ``docker push``
  (``APIs/DistributedImages/python-dist.dockerfile:1-11``);
- **training**: ``CheckpointManager`` save/restore of params + opt state +
  step, so fine-tuning survives preemption (TPU pods are preemptible; the
  reference's AKS GPU pools assumed long-lived nodes).

Orbax handles sharded arrays natively: on restore, arrays are placed directly
onto the mesh via the target tree's shardings — no host-memory detour on
multi-host slices.
"""

from __future__ import annotations

import logging
from typing import Any

import jax
import numpy as np
import orbax.checkpoint as ocp

log = logging.getLogger("ai4e_tpu.checkpoint")


def save_params(path: str, params: Any) -> None:
    """Write a single params pytree (serving checkpoint). ``path`` must be
    absolute; an existing checkpoint at the path is replaced."""
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(path, params, force=True)
    ckptr.wait_until_finished()
    ckptr.close()


def load_params(path: str, like: Any | None = None) -> Any:
    """Restore a params pytree. With ``like`` (a pytree of arrays or
    ShapeDtypeStructs, possibly sharded), arrays restore to its shapes,
    dtypes, and shardings — pass the model's init tree to land params
    directly on the mesh."""
    ckptr = ocp.StandardCheckpointer()
    if like is not None:
        target = jax.tree.map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype,
                                           sharding=getattr(a, "sharding", None)),
            like)
        out = ckptr.restore(path, target)
    else:
        out = ckptr.restore(path)
    ckptr.close()
    return out


class CheckpointManager:
    """Rolling train-state checkpoints: params + optimizer state + step.

    Thin policy layer over ``orbax.CheckpointManager``: keep the latest
    ``max_to_keep``, save every ``save_interval_steps``, resume from the
    newest on restart. The task journal plays the same role for tasks; this
    plays it for weights.
    """

    def __init__(self, directory: str, max_to_keep: int = 3,
                 save_interval_steps: int = 1):
        self._mgr = ocp.CheckpointManager(
            directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                save_interval_steps=save_interval_steps,
            ),
        )

    def save(self, step: int, params: Any, opt_state: Any | None = None,
             extra: dict | None = None) -> bool:
        """Save (respecting the save-interval policy). Returns True if a
        checkpoint was actually written."""
        items = {"params": ocp.args.StandardSave(params)}
        if opt_state is not None:
            items["opt_state"] = ocp.args.StandardSave(opt_state)
        if extra:
            items["extra"] = ocp.args.JsonSave(extra)
        saved = self._mgr.save(step, args=ocp.args.Composite(**items))
        return bool(saved)

    def latest_step(self) -> int | None:
        return self._mgr.latest_step()

    def restore(self, params_like: Any, opt_state_like: Any | None = None,
                step: int | None = None) -> dict:
        """Restore the given (or latest) step onto the templates' shardings.
        Returns {"step", "params", "opt_state"?, "extra"?}."""
        step = self._mgr.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError("no checkpoint to restore")

        def as_struct(tree):
            return jax.tree.map(
                lambda a: jax.ShapeDtypeStruct(
                    np.shape(a), a.dtype,
                    sharding=getattr(a, "sharding", None)), tree)

        items = {"params": ocp.args.StandardRestore(as_struct(params_like))}
        if opt_state_like is not None:
            items["opt_state"] = ocp.args.StandardRestore(
                as_struct(opt_state_like))
        saved_items = self._mgr.item_metadata(step)
        if saved_items is not None and "extra" in saved_items:
            items["extra"] = ocp.args.JsonRestore()
        restored = self._mgr.restore(step, args=ocp.args.Composite(**items))
        out = {"step": step, "params": restored["params"]}
        if opt_state_like is not None:
            out["opt_state"] = restored["opt_state"]
        if "extra" in items:
            out["extra"] = restored["extra"]
        return out

    def wait(self) -> None:
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.wait_until_finished()
        self._mgr.close()


def save_trainer(mgr: CheckpointManager, trainer, step: int) -> bool:
    """Checkpoint a ``train.Trainer``'s full state."""
    return mgr.save(step, trainer.params, trainer.opt_state)


def resume_trainer(mgr: CheckpointManager, trainer) -> int:
    """Restore the newest checkpoint into a ``train.Trainer`` in place;
    returns the restored step (0 if nothing to restore)."""
    try:
        restored = mgr.restore(trainer.params, trainer.opt_state)
    except FileNotFoundError:
        return 0
    trainer.params = restored["params"]
    trainer.opt_state = restored["opt_state"]
    return restored["step"]
