"""AIL020/AIL021/AIL022 — the balance family (docs/analysis.md catalog;
docs/concurrency.md "paired-effect conservation contract").

AIL020 flags paired effects (``ai4e_tpu/analysis/balance.py`` holds the
engine and the declarative pair table) whose close does not dominate
every function exit. AIL021 applies the two-sided drift check (the
AIL006/010/016 family) to durable truth: every journal record marker the
task store writes must have a replay branch, and every replay branch must
have a writer. AIL022 is the self-honesty rule: every declared pair
symbol must still resolve to real code, so a rename cannot silently
disarm AIL020.
"""

from __future__ import annotations

import ast

from ..balance import PAIR_SPECS, check_all
from ..core import Finding, ModuleContext, ProjectContext, ProjectRule, Rule

_KIND_HINTS = {
    "return": "the return at line {at} is not covered by a matched close "
              "— close before returning or move the close to a finally",
    "raise": "the raise at line {at} is not covered by a matched close — "
             "close before re-raising or move the close to a finally",
    "end": "the straight-line path reaches line {at} without an "
           "unconditional close — close on every path or use a finally",
    "abandonment": "a cancelled await at line {at} abandons the frame "
                   "before the close runs — protect the span with "
                   "try/finally or a context manager",
}


class UnbalancedPairedEffect(Rule):
    rule_id = "AIL020"
    name = "unbalanced-paired-effect"
    description = ("a paired effect (probe slot, inflight count, limiter "
                   "slot, gauge, ledger buffer) is opened on a path where "
                   "its close does not cover every exit")
    family = "balance"

    def check_module(self, ctx: ModuleContext):
        out: list[Finding] = []
        stack: list[str] = []

        def visit(node: ast.AST) -> None:
            if isinstance(node, ast.ClassDef):
                stack.append(node.name)
                for child in ast.iter_child_nodes(node):
                    visit(child)
                stack.pop()
                return
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                symbol = ".".join([*stack, node.name])
                for e in check_all(node):
                    spec = e.spec
                    verb = self._verb(e.open_snippet_node)
                    recv = f"{e.receiver}.{verb}" if e.receiver else verb
                    snippet = ctx.snippet(e.open_line)
                    hint = _KIND_HINTS[e.kind].format(at=e.at_line)
                    out.append(Finding(
                        rule=self.rule_id, path=ctx.path,
                        line=e.open_line, col=e.open_col,
                        message=(f"paired effect '{spec.name}' opened "
                                 f"by {recv}(...) leaks on the "
                                 f"{e.kind} path: {hint} "
                                 f"(closes: "
                                 f"{'/'.join(spec.closes)})"),
                        symbol=symbol, snippet=snippet,
                        fingerprint_key=(
                            f"AIL020|{spec.name}|{symbol}|{e.kind}|"
                            f"{' '.join(snippet.split())}")))
                stack.append(node.name)
                for child in ast.iter_child_nodes(node):
                    visit(child)
                stack.pop()
                return
            for child in ast.iter_child_nodes(node):
                visit(child)

        visit(ctx.tree)
        return out

    @staticmethod
    def _verb(call: ast.AST) -> str:
        func = getattr(call, "func", None)
        if isinstance(func, ast.Attribute):
            return func.attr
        if isinstance(func, ast.Name):
            return func.id
        return "<call>"


# -- AIL021 ------------------------------------------------------------------

#: The durable-truth surface AIL021 audits. Path suffix so test fixtures
#: can stand up their own store module under a tmp dir.
_STORE_SUFFIX = "taskstore/store.py"
_SINKS = frozenset({"_append", "_write_own_line", "emit"})
_REPLAY_FN = "_apply_replay_record"


def _const_str(node: ast.AST) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _is_true(node: ast.AST) -> bool:
    return isinstance(node, ast.Constant) and node.value is True


class _StoreIndex:
    """Parent map + function table for one store module."""

    def __init__(self, tree: ast.Module):
        self.parents: dict[ast.AST, ast.AST] = {}
        self.funcs: dict[str, ast.AST] = {}
        for node in ast.walk(tree):
            for child in ast.iter_child_nodes(node):
                self.parents[child] = node
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.funcs.setdefault(node.name, node)

    def enclosing_fn(self, node: ast.AST):
        while node in self.parents:
            node = self.parents[node]
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return node
        return None

    def symbol(self, node: ast.AST) -> str:
        names: list[str] = []
        while node in self.parents:
            node = self.parents[node]
            if isinstance(node, (ast.ClassDef, ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                names.append(node.name)
        return ".".join(reversed(names))


class JournalReplayRoundTrip(ProjectRule):
    rule_id = "AIL021"
    name = "journal-replay-round-trip"
    description = ("every journal record marker the task store writes "
                   "must have a replay branch, and every replay branch "
                   "must have a writer — one-sided protocol silently "
                   "drops durable state at restart")
    family = "balance"

    def check_project(self, ctx: ProjectContext):
        out: list[Finding] = []
        for m in ctx.modules:
            if m.path.endswith(_STORE_SUFFIX):
                out.extend(self._check_store(m))
        return out

    # -- writer side ---------------------------------------------------------

    def _record_keys(self, expr: ast.AST, fn, idx: _StoreIndex,
                     depth: int, inline: bool,
                     keys: dict[str, tuple[int, bool]]) -> None:
        """Accumulate ``key -> (line, is_marker)`` from a record
        expression: dict literals, locals (plus their subscript stores),
        and one level of record-builder helpers. Unresolvable expressions
        (``task.to_dict()``) contribute nothing — payload, not protocol."""
        if depth > 2:
            return
        if isinstance(expr, ast.Dict):
            small = inline and len(expr.keys) <= 2
            for k, v in zip(expr.keys, expr.values):
                key = _const_str(k) if k is not None else None
                if key is None:
                    continue
                marker = _is_true(v) or small
                prev = keys.get(key)
                if prev is None or (marker and not prev[1]):
                    keys[key] = (k.lineno, marker)
            return
        if isinstance(expr, ast.Name) and fn is not None:
            name = expr.id
            for node in ast.walk(fn):
                if isinstance(node, ast.Assign) and any(
                        isinstance(t, ast.Name) and t.id == name
                        for t in node.targets):
                    self._record_keys(node.value, fn, idx, depth + 1,
                                      False, keys)
                if (isinstance(node, ast.Assign)
                        and len(node.targets) == 1
                        and isinstance(node.targets[0], ast.Subscript)
                        and isinstance(node.targets[0].value, ast.Name)
                        and node.targets[0].value.id == name):
                    key = _const_str(node.targets[0].slice)
                    if key is not None:
                        marker = _is_true(node.value)
                        prev = keys.get(key)
                        if prev is None or (marker and not prev[1]):
                            keys[key] = (node.lineno, marker)
            return
        if isinstance(expr, ast.Call):
            callee = None
            if isinstance(expr.func, ast.Attribute):
                callee = expr.func.attr
            elif isinstance(expr.func, ast.Name):
                callee = expr.func.id
            helper = idx.funcs.get(callee or "")
            if helper is not None:
                for node in ast.walk(helper):
                    if isinstance(node, ast.Return) and node.value is not None:
                        self._record_keys(node.value, helper, idx,
                                          depth + 1, False, keys)

    # -- replay side ---------------------------------------------------------

    @staticmethod
    def _replay_keys(replay, idx: _StoreIndex):
        """(consulted, branch) key sets plus ``key -> line`` for branch
        keys. Branch keys are discriminators consulted inside a test —
        the keys that select which replay arm applies."""
        rec_names = {a.arg for a in replay.args.args
                     if a.arg not in ("self", "cls")}
        test_ids: set[int] = set()
        for node in ast.walk(replay):
            tests = []
            if isinstance(node, (ast.If, ast.While)):
                tests.append(node.test)
            elif isinstance(node, ast.IfExp):
                tests.append(node.test)
            for t in tests:
                test_ids.update(id(n) for n in ast.walk(t))
        consulted: set[str] = set()
        branch: dict[str, int] = {}

        def note(key: str, node: ast.AST) -> None:
            consulted.add(key)
            if id(node) in test_ids and key not in branch:
                branch[key] = node.lineno

        for node in ast.walk(replay):
            if isinstance(node, ast.Compare):
                key = _const_str(node.left)
                if (key is not None
                        and any(isinstance(op, (ast.In, ast.NotIn))
                                for op in node.ops)
                        and any(isinstance(c, ast.Name)
                                and c.id in rec_names
                                for c in node.comparators)):
                    note(key, node)
            if (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "get"
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id in rec_names and node.args):
                key = _const_str(node.args[0])
                if key is not None:
                    note(key, node)
            if (isinstance(node, ast.Subscript)
                    and isinstance(node.value, ast.Name)
                    and node.value.id in rec_names):
                key = _const_str(node.slice)
                if key is not None:
                    note(key, node)
        return consulted, branch

    def _check_store(self, m: ModuleContext):
        idx = _StoreIndex(m.tree)
        written: dict[str, tuple[int, bool]] = {}
        writer_syms: dict[str, str] = {}
        sink_calls = 0
        for node in ast.walk(m.tree):
            if not isinstance(node, ast.Call):
                continue
            callee = None
            if isinstance(node.func, ast.Attribute):
                callee = node.func.attr
            elif isinstance(node.func, ast.Name):
                callee = node.func.id
            if callee not in _SINKS:
                continue
            sink_calls += 1
            fn = idx.enclosing_fn(node)
            before = set(written)
            for arg in node.args:
                self._record_keys(arg, fn, idx, 0,
                                  isinstance(arg, ast.Dict), written)
            for key in set(written) - before:
                writer_syms[key] = idx.symbol(node)

        replay = idx.funcs.get(_REPLAY_FN)

        def finding(line: int, message: str, symbol: str,
                    fp: str) -> Finding:
            return Finding(rule=self.rule_id, path=m.path, line=line,
                           col=0, message=message, symbol=symbol,
                           snippet=m.snippet(line), fingerprint_key=fp)

        if replay is None:
            if sink_calls:
                yield finding(
                    1, f"journal writers found but no {_REPLAY_FN}() — "
                       "the replay entrypoint was renamed or removed; "
                       "AIL021 cannot verify the round-trip", "",
                    "AIL021|no-replay-entrypoint")
            return
        if not sink_calls:
            yield finding(
                replay.lineno,
                f"{_REPLAY_FN}() exists but no journal writer calls "
                "(_append/_write_own_line) were found — the writer "
                "surface was renamed; AIL021 cannot verify the "
                "round-trip", _REPLAY_FN, "AIL021|no-writer-surface")
            return

        consulted, branch = self._replay_keys(replay, idx)
        for key, (line, marker) in sorted(written.items()):
            if marker and key not in consulted:
                yield finding(
                    line,
                    f"journal record marker '{key}' is written but "
                    f"{_REPLAY_FN}() never consults it — this record "
                    "type is silently dropped when the journal replays "
                    "at restart", writer_syms.get(key, ""),
                    f"AIL021|writer-without-replay|{key}")
        for key, line in sorted(branch.items()):
            if key not in written:
                yield finding(
                    line,
                    f"replay branch consults '{key}' but no journal "
                    "writer ever emits it — dead protocol, or the "
                    "writer was renamed away", idx.symbol(replay),
                    f"AIL021|replay-without-writer|{key}")


# -- AIL022 ------------------------------------------------------------------


class PairSpecDrift(ProjectRule):
    rule_id = "AIL022"
    name = "pair-spec-drift"
    description = ("a declared AIL020 pair symbol no longer resolves to "
                   "real code — a rename silently disarmed the "
                   "conservation check")
    family = "balance"

    def check_project(self, ctx: ProjectContext):
        anchored = [s for s in PAIR_SPECS if s.anchor]
        if not anchored:
            return
        resolved: set[str] | None = None
        for spec in anchored:
            anchor = next((m for m in ctx.modules
                           if m.path.endswith(spec.anchor)), None)
            if anchor is None:
                continue  # pair's home surface not in this scan
            if resolved is None:
                resolved = set()
                for m in ctx.modules:
                    for node in ast.walk(m.tree):
                        if isinstance(node, (ast.FunctionDef,
                                             ast.AsyncFunctionDef)):
                            resolved.add(node.name)
                        elif isinstance(node, ast.Attribute):
                            resolved.add(node.attr)
                        elif (isinstance(node, ast.Call)
                                and isinstance(node.func, ast.Name)):
                            resolved.add(node.func.id)
            for sym in (*spec.opens, *spec.closes):
                if sym not in resolved:
                    yield Finding(
                        rule=self.rule_id, path=anchor.path, line=1,
                        col=0,
                        message=(f"pair spec '{spec.name}' names "
                                 f"'{sym}' but it resolves to no "
                                 "function or attribute in the scanned "
                                 "tree — update PAIR_SPECS in "
                                 "analysis/balance.py or AIL020 is "
                                 "silently disarmed"),
                        symbol="", snippet=anchor.snippet(1),
                        fingerprint_key=f"AIL022|{spec.name}|{sym}")
