"""AIL008 — a lock held across a slow (network/timer-bound) ``await``,
plus inconsistent lock-acquisition order.

The bug class: ``async with self._lock: await session.post(...)`` pins the
lock for the full round-trip — every other coroutine needing it queues
behind one slow backend, converting a per-request latency into a
platform-wide convoy (and with ``threading.Lock`` it blocks the entire
event loop). The platform's convention is the opposite shape: compute the
decision under the lock, do the I/O outside it (see ``taskstore.store``'s
blob handling, ``rescache.cache``'s fill protocol).

Two checks, one rule id:

- **slow await under a lock** — inside a ``with``/``async with`` whose
  context manager resolves to a lock (name heuristic: the final attribute
  segment contains ``lock``, or a direct ``asyncio.Lock()`` /
  ``threading.Lock()`` / ``RLock()`` / ``Semaphore()`` call), an awaited
  call whose final name is network/timer-bound (``post``/``get``/
  ``request``/``read``/``sleep``/``wait_for``/…) is flagged. Awaiting a
  *fast* coroutine under a lock is fine and common.
- **acquisition-order drift** — per module, every function's nested lock
  pairs are collected (``with A: … with B:`` → ``A→B``); two functions
  acquiring the same two locks in opposite orders deadlock the first time
  their schedules interleave, so both sites are flagged.
"""

from __future__ import annotations

import ast

from ..core import Rule, dotted_name, enclosing_symbol, import_aliases

LOCK_FACTORY_TAILS = frozenset({"Lock", "RLock", "Semaphore",
                                "BoundedSemaphore", "Condition"})
# Awaited-call name tails that mean "this await parks for I/O or time":
# HTTP verbs + socket/stream verbs + timers/waits. Deliberately NOT
# included: ``to_thread`` / ``run_in_executor`` — offloading CPU/disk work
# under a dedicated lock is a serialization *idiom* (the worker's
# checkpoint-reload lock exists precisely to hold reloads across the
# swap), not a convoy bug.
SLOW_AWAIT_TAILS = frozenset({
    "post", "get", "put", "patch", "delete", "head", "request", "fetch",
    "urlopen", "connect", "send", "recv", "receive", "read", "text",
    "json", "sleep", "wait", "wait_for", "drain", "gather", "subscribe",
})


def _chain_tail(node: ast.AST) -> str | None:
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _lock_name(expr: ast.AST, aliases: dict) -> str | None:
    """The canonical name of a lock-ish context manager, or None.

    ``self._lock`` → ``self._lock``; ``asyncio.Lock()`` → its dotted
    name; anything whose final segment doesn't look like a lock → None.
    """
    node = expr
    if isinstance(node, ast.Call):
        name = dotted_name(node.func, aliases)
        if name and name.split(".")[-1] in LOCK_FACTORY_TAILS:
            return name
        return None
    tail = _chain_tail(node)
    # Word-boundary match, not substring: "_block"/"blocklist" contain
    # "lock" but hold no lock — a CI-blocking rule must not misclassify
    # them. Real lock names segment cleanly (_lock, _reload_lock, …).
    if tail and any(seg in ("lock", "rlock", "locks")
                    for seg in tail.lower().split("_")):
        parts = []
        cur = node
        while isinstance(cur, ast.Attribute):
            parts.append(cur.attr)
            cur = cur.value
        if isinstance(cur, ast.Name):
            parts.append(cur.id)
            return ".".join(reversed(parts))
        return tail
    return None


class _Visitor(ast.NodeVisitor):
    def __init__(self, rule, ctx):
        self.rule = rule
        self.ctx = ctx
        self.aliases = import_aliases(ctx.tree)
        self.findings = []
        self._stack: list[ast.AST] = []
        # Locks currently held (innermost last) while visiting.
        self._held: list[tuple[str, ast.AST]] = []
        # (outer, inner) -> first acquisition site, for order tracking.
        self.pairs: dict[tuple[str, str], ast.AST] = {}

    # -- scope bookkeeping ---------------------------------------------------

    def _enter(self, node):
        self._stack.append(node)
        held, self._held = self._held, []  # locks don't cross def bounds
        self.generic_visit(node)
        self._held = held
        self._stack.pop()

    visit_ClassDef = _enter
    visit_FunctionDef = _enter
    visit_AsyncFunctionDef = _enter

    # -- with/async with -----------------------------------------------------

    def _visit_with(self, node):
        acquired = []
        for item in node.items:
            name = _lock_name(item.context_expr, self.aliases)
            if name is None:
                continue
            # Pair against locks already held AND earlier items of THIS
            # statement — `async with a, b:` enters left-to-right, so it
            # establishes the a->b order exactly like nesting does.
            for outer, _site in self._held + acquired:
                key = (outer, name)
                self.pairs.setdefault(key, node)
            acquired.append((name, node))
        self._held.extend(acquired)
        self.generic_visit(node)
        if acquired:
            del self._held[-len(acquired):]

    visit_With = _visit_with
    visit_AsyncWith = _visit_with

    # -- awaits under a held lock --------------------------------------------

    def visit_Await(self, node):
        if self._held:
            tail = None
            value = node.value
            if isinstance(value, ast.Call):
                tail = _chain_tail(value.func)
            if tail in SLOW_AWAIT_TAILS:
                lock = self._held[-1][0]
                self.findings.append(self.ctx.finding(
                    self.rule.rule_id, node,
                    f"await {tail}() while holding {lock} — the lock is "
                    "pinned for a network/timer-bound round trip, so every "
                    "other coroutine needing it convoys behind one slow "
                    "peer (compute under the lock, do the I/O outside it)",
                    symbol=enclosing_symbol(self._stack)))
        self.generic_visit(node)


class LockAcrossSlowAwait(Rule):
    rule_id = "AIL008"
    name = "lock-across-slow-await"
    description = ("a lock held across a network/timer-bound await convoys "
                   "the loop; opposite-order double acquisitions deadlock")

    def check_module(self, ctx):
        v = _Visitor(self, ctx)
        v.visit(ctx.tree)
        findings = v.findings
        # Order drift: (A, B) and (B, A) both acquired somewhere in this
        # module — the first interleaving of those two code paths deadlocks.
        reported = set()
        for (outer, inner), site in sorted(
                v.pairs.items(), key=lambda kv: (kv[1].lineno, kv[0])):
            if (inner, outer) in v.pairs and outer != inner:
                pair = tuple(sorted((outer, inner)))
                if pair in reported:
                    continue
                reported.add(pair)
                other = v.pairs[(inner, outer)]
                findings.append(ctx.finding(
                    self.rule_id, site,
                    f"lock order {outer} -> {inner} here, but "
                    f"{inner} -> {outer} at line {other.lineno} — opposite "
                    "acquisition orders deadlock when the two paths "
                    "interleave (pick one order and stick to it)",
                ))
        return findings
