"""AIL001 — blocking call inside ``async def``.

The bug class: one ``time.sleep`` (or synchronous HTTP/subprocess/file
I/O) on a coroutine path stalls the WHOLE event loop — every in-flight
request on that loop eats the stall as tail latency, and under load the
gateway's adaptive limiter reads it as backend congestion and sheds.
The platform's convention is explicit: sleeps are ``asyncio.sleep``,
outbound HTTP is aiohttp, and genuinely-blocking work hops off the loop
via ``asyncio.to_thread`` / ``run_in_executor`` (which pass the callable
without calling it, so they never trip this rule).
"""

from __future__ import annotations

import ast

from ..core import Rule, dotted_name, enclosing_symbol, import_aliases

# Exact canonical call names that block the loop.
BLOCKING_CALLS = frozenset({
    "time.sleep",
    "os.system",
    "os.popen",
    "os.wait",
    "os.waitpid",
    "socket.create_connection",
    "socket.getaddrinfo",
    "socket.gethostbyname",
    "subprocess.run",
    "subprocess.call",
    "subprocess.check_call",
    "subprocess.check_output",
    "subprocess.getoutput",
    "subprocess.getstatusoutput",
    "subprocess.Popen",
    "urllib.request.urlopen",
    "urllib.request.urlretrieve",
})

# Module prefixes where EVERY call is synchronous network I/O.
BLOCKING_PREFIXES = ("requests.", "http.client.", "urllib3.")


class _Visitor(ast.NodeVisitor):
    def __init__(self, rule: "BlockingCallInAsync", ctx):
        self.rule = rule
        self.ctx = ctx
        self.aliases = import_aliases(ctx.tree)
        self.findings = []
        # Innermost function kind: True inside async def, False inside a
        # nested sync def/lambda (a sync helper defined in a coroutine runs
        # wherever it is CALLED — commonly an executor — so it resets the
        # context rather than inheriting it).
        self._stack: list[ast.AST] = []
        self._async: list[bool] = []

    def _enter(self, node, is_async: bool):
        self._stack.append(node)
        self._async.append(is_async)
        self.generic_visit(node)
        self._async.pop()
        self._stack.pop()

    def visit_FunctionDef(self, node):
        self._enter(node, False)

    def visit_AsyncFunctionDef(self, node):
        self._enter(node, True)

    def visit_Lambda(self, node):
        self._stack.append(node)
        self._async.append(False)
        self.generic_visit(node)
        self._async.pop()
        self._stack.pop()

    def visit_ClassDef(self, node):
        self._stack.append(node)
        self.generic_visit(node)
        self._stack.pop()

    def visit_Call(self, node):
        if self._async and self._async[-1]:
            name = dotted_name(node.func, self.aliases)
            if name and (name in BLOCKING_CALLS
                         or name.startswith(BLOCKING_PREFIXES)):
                self.findings.append(self.ctx.finding(
                    self.rule.rule_id, node,
                    f"blocking call {name}() inside async def stalls the "
                    "event loop (use the asyncio/aiohttp equivalent or "
                    "asyncio.to_thread)",
                    symbol=enclosing_symbol(self._stack)))
        self.generic_visit(node)


class BlockingCallInAsync(Rule):
    rule_id = "AIL001"
    name = "blocking-call-in-async"
    description = ("time.sleep / synchronous HTTP / subprocess / socket "
                   "calls inside async def stall the event loop")

    def check_module(self, ctx):
        v = _Visitor(self, ctx)
        v.visit(ctx.tree)
        return v.findings
