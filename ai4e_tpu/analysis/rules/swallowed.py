"""AIL005 — broad exception handler that swallows silently.

The bug class: ``except Exception:`` (or bare ``except:``) whose body
neither logs, re-raises, nor counts a metric. In a serving platform these
are where real failures go to disappear — a store probe that starts
erroring under load, a listener that dies on every event — with zero
operator signal. The platform's own broad handlers are legitimate
("telemetry must not break serving", "the dispatcher must never die") and
they all LOG; this rule enforces that the next one does too.

Accepted evidence inside the handler body:

- a ``raise`` (bare re-raise or a new exception),
- a logging call — any ``.debug/.info/.warning/.error/.exception/
  .critical/.log`` attribute call, or ``print`` as a last resort,
- a metric write (``.inc()`` / ``.observe()`` / ``.set(value)`` — a bare
  ``.set()`` is Event signalling, not telemetry, and does not count),
- a ``return``/assignment path is NOT evidence — returning a default is
  exactly how swallowing looks.

Intentionally-silent handlers carry ``# ai4e: noqa[AIL005] — reason`` on
the ``except`` line; the reason is part of the contract.
"""

from __future__ import annotations

import ast

from ..core import Rule, enclosing_symbol

LOG_METHODS = frozenset({"debug", "info", "warning", "error", "exception",
                         "critical", "log"})
BROAD = frozenset({"Exception", "BaseException"})


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    names = [t] if not isinstance(t, ast.Tuple) else list(t.elts)
    for n in names:
        if isinstance(n, ast.Name) and n.id in BROAD:
            return True
        if isinstance(n, ast.Attribute) and n.attr in BROAD:
            return True
    return False


def _has_evidence(handler: ast.ExceptHandler) -> bool:
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Attribute):
                if f.attr in LOG_METHODS or f.attr in {"inc", "observe"}:
                    return True
                if f.attr == "set" and (node.args or node.keywords):
                    # Gauge.set(value) is metric evidence; a bare .set()
                    # is asyncio/threading Event signalling — ubiquitous
                    # in shutdown paths and NOT an operator signal, so it
                    # must not satisfy the rule.
                    return True
            if isinstance(f, ast.Name) and f.id == "print":
                return True
    return False


class _Visitor(ast.NodeVisitor):
    def __init__(self, rule, ctx):
        self.rule = rule
        self.ctx = ctx
        self.findings = []
        self._stack: list[ast.AST] = []

    def _enter(self, node):
        self._stack.append(node)
        self.generic_visit(node)
        self._stack.pop()

    visit_ClassDef = _enter
    visit_FunctionDef = _enter
    visit_AsyncFunctionDef = _enter

    def visit_ExceptHandler(self, node):
        if _is_broad(node) and not _has_evidence(node):
            kind = ("bare except" if node.type is None
                    else "except Exception")
            self.findings.append(self.ctx.finding(
                self.rule.rule_id, node,
                f"{kind} swallows silently — log it, count it "
                "(ai4e_*_errors_total), re-raise, or justify with "
                "`# ai4e: noqa[AIL005] — reason`",
                symbol=enclosing_symbol(self._stack)))
        self.generic_visit(node)


class SwallowedException(Rule):
    rule_id = "AIL005"
    name = "swallowed-exception"
    description = ("broad except handlers must log, count a metric, or "
                   "re-raise — silence needs a written justification")

    def check_module(self, ctx):
        v = _Visitor(self, ctx)
        v.visit(ctx.tree)
        return v.findings
