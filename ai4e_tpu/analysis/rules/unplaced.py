"""AIL014 — device transfer without an explicit placement on the serving path.

The bug class: PR 17 made device placement declarative — a worker's mesh
layout is a validated ``MeshSpec``, batches land via ``NamedSharding``
batch-axis placements, params via partition rules, and outputs come back
through the one blessed fetch helper (``runtime/mesh/placement.py``). A
bare ``jax.device_put(x)`` pasted under ``runtime/`` or ``parallel/``
silently re-introduces the pre-mesh behavior: the array lands wherever
JAX's default device points (device 0 of however many the process sees),
which *works* on a single-device dev box and then hot-loops one core of
an 8-device serving mesh — or worse, desyncs a multi-process slice whose
followers placed the same array differently. Same for ``device_get``:
an unmediated fetch bypasses the replicated-output contract the fetch
helper documents (and is invisible to any future remote-transfer
accounting), so every device→host read routes through
``placement.fetch_to_host`` — the ONE module this rule does not scan.

A transfer is *placed* when it states where the data goes:

- ``jax.device_put(x, sharding_or_device)`` — second positional arg;
- ``jax.device_put(x, device=...)`` / ``(x, sharding=...)`` /
  ``(x, dst_sharding=...)`` — any placement keyword.

``jax.device_put(x)`` alone is the finding.
"""

from __future__ import annotations

import ast

from ..core import Rule, dotted_name, enclosing_symbol, import_aliases

#: Only the serving device path is in scope — model code, benches, and
#: tests legitimately use default placements.
SCOPE_PARTS = ("runtime/", "parallel/")
#: The blessed transfer-helper module (see its docstring).
EXEMPT_SUFFIX = "runtime/mesh/placement.py"

_PLACEMENT_KWARGS = {"device", "sharding", "dst_sharding", "donate"}


class UnplacedDeviceTransfer(Rule):
    rule_id = "AIL014"
    name = "unplaced-device-transfer"
    description = ("device transfers under runtime/ and parallel/ must "
                   "state their placement: device_put needs a sharding/"
                   "device argument, device_get goes through "
                   "runtime/mesh/placement.fetch_to_host")

    def check_module(self, ctx):
        path = ctx.path.replace("\\", "/")
        if (not any(part in path for part in SCOPE_PARTS)
                or path.endswith(EXEMPT_SUFFIX)):
            return []
        aliases = import_aliases(ctx.tree)
        rule = self

        class _Visitor(ast.NodeVisitor):
            def __init__(self):
                self.findings = []
                self._stack: list[ast.AST] = []

            def _enter(self, node):
                self._stack.append(node)
                self.generic_visit(node)
                self._stack.pop()

            visit_ClassDef = _enter
            visit_FunctionDef = _enter
            visit_AsyncFunctionDef = _enter

            def visit_Call(self, node):
                name = dotted_name(node.func, aliases)
                if name == "jax.device_put":
                    placed = (len(node.args) >= 2
                              or any(kw.arg in _PLACEMENT_KWARGS
                                     for kw in node.keywords))
                    if not placed:
                        self.findings.append(ctx.finding(
                            rule.rule_id, node,
                            "jax.device_put without a placement lands on "
                            "JAX's default device — pass the NamedSharding "
                            "(runtime/mesh/placement.batch_placement) or "
                            "target device explicitly",
                            symbol=enclosing_symbol(self._stack)))
                elif name == "jax.device_get":
                    self.findings.append(ctx.finding(
                        rule.rule_id, node,
                        "bare jax.device_get on the serving path — route "
                        "device→host fetches through "
                        "runtime/mesh/placement.fetch_to_host (the one "
                        "sanctioned transfer helper)",
                        symbol=enclosing_symbol(self._stack)))
                self.generic_visit(node)

        visitor = _Visitor()
        visitor.visit(ctx.tree)
        return visitor.findings
