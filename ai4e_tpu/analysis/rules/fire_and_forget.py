"""AIL004 — fire-and-forget ``create_task`` / ``ensure_future``.

The bug class: spawning a task and dropping the handle. Two failure
modes, both silent. (1) The event loop holds only a WEAK reference to
tasks — a dropped handle can be garbage-collected mid-flight and the
coroutine simply stops running. (2) An exception raised inside the task
is reported nowhere until interpreter shutdown ("Task exception was
never retrieved"), long after the context that could have handled it is
gone. The platform idiom (``service/app.py``, ``broker/push.py``) is to
add the task to a holder set with a done-callback discard::

    t = loop.create_task(coro())
    self._tasks.add(t)
    t.add_done_callback(self._tasks.discard)

The rule flags spawn calls used as bare expression statements — result
not assigned, awaited, passed as an argument, or chained into
``.add_done_callback``.
"""

from __future__ import annotations

import ast

from ..core import Rule, enclosing_symbol

SPAWN_NAMES = frozenset({"create_task", "ensure_future"})


class _Visitor(ast.NodeVisitor):
    def __init__(self, rule, ctx):
        self.rule = rule
        self.ctx = ctx
        self.findings = []
        self._stack: list[ast.AST] = []

    def _enter(self, node):
        self._stack.append(node)
        self.generic_visit(node)
        self._stack.pop()

    visit_ClassDef = _enter
    visit_FunctionDef = _enter
    visit_AsyncFunctionDef = _enter

    def visit_Expr(self, node):
        call = node.value
        if isinstance(call, ast.Call):
            name = None
            if isinstance(call.func, ast.Attribute):
                name = call.func.attr
            elif isinstance(call.func, ast.Name):
                name = call.func.id
            if name in SPAWN_NAMES:
                self.findings.append(self.ctx.finding(
                    self.rule.rule_id, node,
                    f"result of {name}() dropped — the task can be "
                    "garbage-collected mid-flight and its exceptions "
                    "vanish; store the handle (holder set + "
                    "add_done_callback discard) or await it",
                    symbol=enclosing_symbol(self._stack)))
        self.generic_visit(node)


class FireAndForgetTask(Rule):
    rule_id = "AIL004"
    name = "fire-and-forget-task"
    description = ("create_task/ensure_future results must be stored, "
                   "awaited, or given a done-callback")

    def check_module(self, ctx):
        v = _Visitor(self, ctx)
        v.visit(ctx.tree)
        return v.findings
