"""AIL013 — unbounded metric label from caller identity.

The bug class: metric labels mint one time series per distinct value, so
a label fed from anything the CALLER controls — a subscription key, a
tenant id, a client identifier pulled from request headers — grows the
registry without bound and hands an attacker a memory lever (one rotated
header per request = one fresh series per request). The gateway has
guarded this by hand since PR 2 (``gateway/router.py`` labels 401s with
the constant ``route="unauthorized"`` precisely because "the path is
attacker-chosen and would grow metric cardinality without bound"), and
PR 16's tenant scope makes it systemic: every per-tenant series must
pass the id through the registry's FROZEN bounded mapper
(``TenantRegistry.tenant_label`` — top-N declared tenants + ``other``,
docs/tenancy.md cardinality policy) instead of labeling with the raw id.

The rule flags metric writes — ``.inc(...)`` / ``.set(...)`` /
``.observe(...)`` / ``.dec(...)`` — whose keyword argument is an
identity-class label name (``tenant``, ``api_key``, ``caller``, ...)
bound to a DYNAMIC value. Blessed shapes, in the spirit of ai4e-lint's
other idiom rules (fix the idiom, not the instance):

- a string constant (``tenant="other"`` — already bounded);
- a call to a ``*_label``/``tenant_label`` mapper (inline bounding);
- a name/attribute whose identifier contains ``label`` (the mapped value
  was computed a line earlier — ``label = reg.tenant_label(tid)``).

Everything else — the raw variable, an f-string, a header read — is the
unbounded series waiting to happen.
"""

from __future__ import annotations

import ast

from ..core import Rule, enclosing_symbol

#: Metric-write method names whose kwargs carry label values.
WRITE_METHODS = frozenset({"inc", "dec", "set", "observe"})
#: Label names that, by platform convention, carry caller identity — the
#: values that MUST be bounded before becoming a series dimension.
IDENTITY_LABELS = frozenset({"tenant", "tenant_id", "api_key",
                             "subscription_key", "caller", "client_id",
                             "identity", "user", "user_id",
                             # Rollout generations are unbounded over a
                             # process lifetime (a weekly reload mints a
                             # new one forever) — the generation_label
                             # mapper (rollout/canary.py) is the blessed
                             # top-N+other fold.
                             "generation"})


def _is_blessed(value: ast.AST) -> bool:
    """Whether a label-value expression is visibly bounded."""
    if isinstance(value, ast.Constant) and isinstance(value.value, str):
        return True
    if isinstance(value, ast.Call):
        fn = value.func
        name = fn.attr if isinstance(fn, ast.Attribute) else (
            fn.id if isinstance(fn, ast.Name) else "")
        return "label" in name
    if isinstance(value, ast.Name):
        return "label" in value.id
    if isinstance(value, ast.Attribute):
        return "label" in value.attr
    return False


class UnboundedMetricLabel(Rule):
    rule_id = "AIL013"
    name = "unbounded-metric-label"
    description = ("identity-class metric labels (tenant=, api_key=, ...) "
                   "must pass through a bounded-cardinality mapper "
                   "(*_label) — raw caller identity mints unbounded "
                   "series")

    def check_module(self, ctx):
        rule = self

        class _Visitor(ast.NodeVisitor):
            def __init__(self):
                self.findings = []
                self._stack: list[ast.AST] = []

            def _enter(self, node):
                self._stack.append(node)
                self.generic_visit(node)
                self._stack.pop()

            visit_ClassDef = _enter
            visit_FunctionDef = _enter
            visit_AsyncFunctionDef = _enter

            def visit_Call(self, node):
                fn = node.func
                if (isinstance(fn, ast.Attribute)
                        and fn.attr in WRITE_METHODS):
                    for kw in node.keywords:
                        if (kw.arg in IDENTITY_LABELS
                                and not _is_blessed(kw.value)):
                            self.findings.append(ctx.finding(
                                rule.rule_id, node,
                                f"metric label {kw.arg}= carries caller "
                                "identity from a dynamic value — pass it "
                                "through the bounded-cardinality mapper "
                                "(TenantRegistry.tenant_label: top-N + "
                                "'other', docs/tenancy.md) before it "
                                "becomes a series dimension",
                                symbol=enclosing_symbol(self._stack)))
                self.generic_visit(node)

        visitor = _Visitor()
        visitor.visit(ctx.tree)
        return visitor.findings
