"""AIL006 — config/docs drift on the ``AI4E_*`` env-var surface.

The bug class: a knob exists in code but no operator can discover it (it
appears in no doc), or a doc names a variable that no longer exists (a
rename that missed the docs — the operator sets it, nothing happens, and
``FrameworkConfig.from_env``'s unknown-variable check may even refuse
startup). Config drift is the quiet variant of an outage: the knob you
need during an incident is the one that was never written down.

Three checks, run once over the whole project:

1. every env var derived from an ``@_env_section("AI4E_X_")`` config
   dataclass field (``AI4E_X_<FIELD>``) appears somewhere under ``docs/``
   or ``README.md``;
2. every direct ``os.environ``/``os.getenv`` read of an ``AI4E_*``
   literal in code appears in the docs too;
3. every ``AI4E_*`` token mentioned in the docs corresponds to a real
   config field or direct read (exact match, or a prefix of one — docs
   may legitimately write ``AI4E_PLATFORM_RESILIENCE*`` for a family).

Out-of-band namespaces (``AI4E_FAULT_*`` fault injection,
``AI4E_CHAOS_*`` chaos-harness seeds) are exempt from check 3's
must-exist-as-config-field requirement — they are read by test/failure
paths, never part of the typed config (``config.py`` exempts them from
its own unknown-variable check for the same reason) — but code reads in
them still must be documented (check 2).
"""

from __future__ import annotations

import ast
import os
import re

# The SAME tuple FrameworkConfig.from_env exempts from its
# unknown-variable check — imported, not copied, so a namespace added
# there can never silently diverge from what this rule enforces
# (config.py is stdlib-only, so the analyzer stays dependency-free).
from ...config import OUT_OF_BAND_ENV_PREFIXES as OUT_OF_BAND
from ..core import Finding, ProjectRule, dotted_name, import_aliases

_TOKEN_RE = re.compile(r"AI4E_[A-Z0-9_]*[A-Z0-9]")
DOC_FILES = ("README.md",)
DOC_DIRS = ("docs",)


def _section_env_names(module) -> list[tuple[str, int, str]]:
    """(env_name, lineno, field) for every ``@_env_section(prefix)`` class
    field in the module."""
    out = []
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.ClassDef):
            continue
        prefix = None
        for dec in node.decorator_list:
            if (isinstance(dec, ast.Call)
                    and isinstance(dec.func, ast.Name)
                    and dec.func.id == "_env_section"
                    and dec.args
                    and isinstance(dec.args[0], ast.Constant)
                    and isinstance(dec.args[0].value, str)):
                prefix = dec.args[0].value
        if prefix is None:
            continue
        for stmt in node.body:
            if (isinstance(stmt, ast.AnnAssign)
                    and isinstance(stmt.target, ast.Name)):
                out.append((prefix + stmt.target.id.upper(),
                            stmt.lineno, stmt.target.id))
    return out


def _direct_env_reads(module) -> list[tuple[str, int]]:
    """(env_name, lineno) for os.environ.get("AI4E_...")/os.getenv/
    environ["AI4E_..."] literals."""
    aliases = import_aliases(module.tree)
    out = []
    for node in ast.walk(module.tree):
        literal = None
        if isinstance(node, ast.Call):
            name = dotted_name(node.func, aliases) or ""
            if name.endswith(("environ.get", "getenv")) and node.args:
                arg = node.args[0]
                if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                    literal = arg.value
        elif isinstance(node, ast.Subscript):
            base = dotted_name(node.value, aliases) or ""
            if base.endswith("environ"):
                sl = node.slice
                if isinstance(sl, ast.Constant) and isinstance(sl.value, str):
                    literal = sl.value
        if literal and literal.startswith("AI4E_"):
            out.append((literal, node.lineno))
    return out


class ConfigDrift(ProjectRule):
    rule_id = "AIL006"
    name = "config-drift"
    description = ("every AI4E_* env var in code must be documented, and "
                   "every documented one must exist in code")

    def check_project(self, ctx):
        findings: list[Finding] = []
        known: dict[str, tuple[str, int]] = {}   # env name -> (path, line)
        for module in ctx.modules:
            for env_name, line, _field in _section_env_names(module):
                known.setdefault(env_name, (module.path, line))
            for env_name, line in _direct_env_reads(module):
                known.setdefault(env_name, (module.path, line))
        doc_tokens = self._doc_tokens(ctx.root)
        if not known and not doc_tokens:
            return findings
        documented = {tok for tok, _loc, _family in doc_tokens}
        # A FAMILY mention must be explicit — the token is followed by "*"
        # (or "_*") in the doc text, e.g. AI4E_PLATFORM_RESILIENCE*.
        # Without that requirement any documented var would silently
        # "document" every future knob that merely extends its name
        # (AI4E_PLATFORM_ADMISSION documenting AI4E_PLATFORM_ADMISSION_FOO),
        # defeating the add-the-doc-row-in-the-same-PR guarantee.
        families = {tok for tok, _loc, family in doc_tokens if family}

        def _snippet(path: str, line: int) -> str:
            try:
                with open(os.path.join(ctx.root, path), encoding="utf-8") as fh:
                    lines = fh.read().splitlines()
                return lines[line - 1].strip() if 0 < line <= len(lines) else ""
            except OSError:
                return ""

        # Checks 1+2: code side must be documented — exactly, or by an
        # explicit starred family mention covering it.
        for env_name, (path, line) in sorted(known.items()):
            if env_name in documented or any(
                    env_name == tok or env_name.startswith(tok + "_")
                    for tok in families):
                continue
            findings.append(Finding(
                self.rule_id, path, line, 0,
                f"{env_name} is read by code but documented nowhere under "
                "docs/ or README.md — operators cannot discover it",
                snippet=_snippet(path, line)))

        # Check 3: doc side must exist in code. Leniently here — prose that
        # names a PREFIX of a real variable ("the AI4E_DEMO knobs") is not
        # drift, it's writing.
        for tok, (doc_path, line), _family in sorted(doc_tokens):
            if tok in known:
                continue
            if any(name.startswith(tok) for name in known):
                continue  # family/prefix mention
            if tok.startswith(OUT_OF_BAND) or any(
                    ns.startswith(tok) for ns in OUT_OF_BAND):
                # In-namespace variable, or the namespace itself named
                # without its trailing underscore ("the AI4E_CHAOS
                # namespace") — prose, not drift.
                continue
            findings.append(Finding(
                self.rule_id, doc_path, line, 0,
                f"docs mention {tok} but no config field or env read "
                "defines it — stale doc or a rename that missed the docs",
                snippet=_snippet(doc_path, line)))
        return findings

    def _doc_tokens(self, root: str
                    ) -> list[tuple[str, tuple[str, int], bool]]:
        """(token, (doc path, line), is_family) — family = explicitly
        starred in the doc text (``AI4E_X_*``)."""
        out = []
        paths: list[str] = []
        for name in DOC_FILES:
            p = os.path.join(root, name)
            if os.path.isfile(p):
                paths.append(p)
        for d in DOC_DIRS:
            base = os.path.join(root, d)
            for dirpath, _dirnames, filenames in os.walk(base):
                paths.extend(os.path.join(dirpath, f)
                             for f in sorted(filenames) if f.endswith(".md"))
        for path in paths:
            rel = os.path.relpath(path, root).replace(os.sep, "/")
            try:
                with open(path, encoding="utf-8") as fh:
                    text = fh.read()
            except OSError:
                continue
            for i, line in enumerate(text.splitlines(), 1):
                for m in _TOKEN_RE.finditer(line):
                    rest = line[m.end():]
                    family = rest.startswith("*") or rest.startswith("_*")
                    out.append((m.group(0), (rel, i), family))
        return out
