"""AIL009 — non-atomic read-modify-write of shared state across an await.

The bug class: ``n = self._busy`` … ``await …`` … ``self._busy = n + 1``.
Single-threaded asyncio makes each *segment between suspension points*
atomic — which is exactly why this pattern is a trap: it LOOKS safe (no
threads!), but the await in the middle lets any other coroutine run the
same read-modify-write on the same attribute, and one of the two writes
is lost. ``self._busy += 1`` with no await in the expression is fine (one
segment); the same logic split across a suspension is not.

What it flags, inside an ``async def`` method of a class:

- ``x = <obj>.attr`` … ≥1 suspension point … ``<obj>.attr = f(x)`` (the
  write's value references the stale local), where ``attr`` is written by
  **more than one method** of the class (a single-writer attribute has
  nobody to race with);
- the one-statement form ``<obj>.attr = f(await g(), <obj>.attr)`` — the
  read and write bracket the await inside a single statement.

Fix idioms: re-read after the await; fold the update into one segment
(``+=`` with no await in the expression); or guard the section with an
``asyncio.Lock`` (held only across the update, not the I/O — AIL008).
"""

from __future__ import annotations

import ast

from ..core import AwaitFlow, Rule, enclosing_symbol


def _attr_chain(node: ast.AST) -> str | None:
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    parts.append(node.id)
    return ".".join(reversed(parts))


def _method_attr_writes(cls: ast.ClassDef) -> dict[str, set[str]]:
    """attr chain (``self.x``) -> names of methods that assign it."""
    writes: dict[str, set[str]] = {}
    for item in cls.body:
        if not isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for node in ast.walk(item):
            targets = []
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                targets = [node.target]
            for t in targets:
                chain = _attr_chain(t)
                if chain and chain.startswith("self."):
                    writes.setdefault(chain, set()).add(item.name)
    return writes


class _MethodChecker:
    def __init__(self, rule, ctx, fn, stack, shared_attrs: set[str]):
        self.rule = rule
        self.ctx = ctx
        self.fn = fn
        self.symbol = enclosing_symbol(stack)
        self.shared = shared_attrs
        self.flow = AwaitFlow(fn)
        self.findings: list = []

    def check(self):
        for node in ast.walk(self.fn):
            if node is not self.fn and node not in self.flow._parent:
                continue  # nested scope
            if not isinstance(node, ast.Assign):
                continue
            for target in node.targets:
                tchain = _attr_chain(target)
                if tchain not in self.shared:
                    continue
                self._check_write(tchain, node)
        return self.findings

    def _check_write(self, chain: str, write: ast.Assign):
        # One-statement form: value awaits AND reads the attr it assigns.
        value_awaits = [n for n in ast.walk(write.value)
                        if isinstance(n, ast.Await)]
        value_reads_attr = any(
            isinstance(n, ast.Attribute) and _attr_chain(n) == chain
            and n is not write.targets[0]
            for n in ast.walk(write.value))
        if value_awaits and value_reads_attr:
            self._flag(chain, write, "the same statement")
            return
        # Split form: find the read this write's value depends on.
        for name_node in ast.walk(write.value):
            if not isinstance(name_node, ast.Name):
                continue
            read = self._read_for(name_node.id, chain, write)
            if read is None:
                continue
            between = self.flow.suspensions_between(read, write)
            if between:
                self._flag(chain, write,
                           f"line {getattr(read, 'lineno', '?')}")
                return

    def _read_for(self, local: str, chain: str,
                  write: ast.Assign) -> ast.AST | None:
        from ..core import _pos
        best = None
        for node in ast.walk(self.fn):
            if node is not self.fn and node not in self.flow._parent:
                continue
            if (isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and node.targets[0].id == local
                    and isinstance(node.value, ast.Attribute)
                    and _attr_chain(node.value) == chain
                    and _pos(node) < _pos(write)):
                if best is None or _pos(node) > _pos(best):
                    best = node
        return best

    def _flag(self, chain: str, write: ast.AST, read_where: str):
        self.findings.append(self.ctx.finding(
            self.rule.rule_id, write,
            f"{chain} is rewritten from a value read at {read_where}, "
            "with a suspension point in between — another coroutine can "
            "run the same read-modify-write in that window and one update "
            "is lost (re-read after the await, fold into one segment, or "
            "guard with an asyncio.Lock)",
            symbol=self.symbol))


class _Visitor(ast.NodeVisitor):
    def __init__(self, rule, ctx):
        self.rule = rule
        self.ctx = ctx
        self.findings = []
        self._stack: list[ast.AST] = []
        self._shared: list[set[str]] = []  # per enclosing class

    def visit_ClassDef(self, node):
        writes = _method_attr_writes(node)
        shared = {chain for chain, methods in writes.items()
                  if len(methods) > 1}
        self._stack.append(node)
        self._shared.append(shared)
        self.generic_visit(node)
        self._shared.pop()
        self._stack.pop()

    def visit_FunctionDef(self, node):
        self._stack.append(node)
        self.generic_visit(node)
        self._stack.pop()

    def visit_AsyncFunctionDef(self, node):
        self._stack.append(node)
        if self._shared and self._shared[-1]:
            self.findings.extend(_MethodChecker(
                self.rule, self.ctx, node, self._stack,
                self._shared[-1]).check())
        self.generic_visit(node)
        self._stack.pop()


class NonatomicReadModifyWrite(Rule):
    rule_id = "AIL009"
    name = "nonatomic-read-modify-write"
    description = ("read of a multi-writer attribute, a suspension point, "
                   "then a dependent write back — a lost-update race")

    def check_module(self, ctx):
        v = _Visitor(self, ctx)
        v.visit(ctx.tree)
        return v.findings
