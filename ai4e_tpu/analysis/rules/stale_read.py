"""AIL007 — guard read goes stale across an ``await`` before the write.

The bug class — every hard concurrency bug PRs 3-4 found by hand had this
shape: a guard reads shared state (a task's terminal status, a breaker's
state), an ``await`` hands the event loop to arbitrary other tasks, and
the dependent write then acts on the stale read. Concrete instances: the
dispatcher's ``_drop_expired`` flipping completed→expired on a redelivery,
push ``_forward`` re-executing a completed task, the half-open breaker's
leaked probe slot. AIL003 checks that terminal-status writes are *guarded*
somewhere in the function; this rule checks the guard is still *valid*
when the write runs — no suspension point between guard and write, or a
visible re-check after the last one.

What it flags, inside an ``async def``:

- a **status write** (``update_task_status`` / ``update_status`` /
  ``complete_task`` / ``fail_task`` / ``_try_update``) whose nearest
  dominating **terminality guard** (``is_terminal`` /
  ``_suppress_duplicate`` / ``_drop_expired`` / ``canonical_status`` /
  ``… in TaskStatus.TERMINAL``) is separated from it by ≥1 suspension
  point, with no re-check between the last suspension and the write;
- a **state-attribute write** (``x.state = …`` / ``x.status = …``) whose
  value the function guarded on the same attribute chain before an
  intervening suspension.

Blessed idioms (never flagged):

- **atomic conditional helpers** — ``update_status_if`` / ``requeue_if``
  re-check under the store lock, so staleness cannot clobber;
- **probe-after-await** — ``if not await tm.is_terminal(t): await
  write(t)``: the probe is itself the last suspension before the write
  (the residual one-hop window is accepted platform-wide,
  docs/concurrency.md);
- any re-check of the guard vocabulary between the last suspension and
  the write.
"""

from __future__ import annotations

import ast

from ..core import AwaitFlow, Rule, enclosing_symbol

# Unconditional status writers (AIL003's set) — the writes whose staleness
# clobbers a terminal task.
STATUS_WRITERS = frozenset({
    "update_task_status", "update_status", "complete_task", "fail_task",
    "_try_update",
})
# Terminality probes: evaluating one of these (re-)establishes the guard.
GUARD_PROBES = frozenset({
    "is_terminal", "_suppress_duplicate", "_drop_expired",
})
GUARD_ATTRS = frozenset({"canonical_status"})
# State attributes the attribute-write half of the rule watches.
STATE_ATTRS = frozenset({"state", "status"})
# Writer shims (the function IS the write plumbing — callers carry the
# guard; AIL003 applies the same exemption).
SHIM_NAMES = STATUS_WRITERS | frozenset({"_update"})


def _call_name(func: ast.AST) -> str | None:
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


def _attr_chain(node: ast.AST) -> str | None:
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    parts.append(node.id)
    return ".".join(reversed(parts))


def _is_guard_expr(node: ast.AST) -> bool:
    """Does this expression (re-)establish a terminality guard?"""
    if isinstance(node, ast.Call) and _call_name(node.func) in GUARD_PROBES:
        return True
    if isinstance(node, ast.Attribute) and node.attr in GUARD_ATTRS:
        return True
    if isinstance(node, ast.Compare):
        for op, comparator in zip(node.ops, node.comparators):
            if isinstance(op, (ast.In, ast.NotIn)) and any(
                    isinstance(n, ast.Attribute) and n.attr == "TERMINAL"
                    for n in ast.walk(comparator)):
                return True
    return False


def _collect_guards(fn: ast.AST, flow: AwaitFlow) -> list[ast.AST]:
    """Guard anchors: every guard expression sitting in an ``if``/``while``
    test (or a boolean/unary expression inside one). The anchor is lifted
    to the enclosing ``Await`` when directly awaited, so the probe's own
    suspension never counts against itself."""
    guards: list[ast.AST] = []
    for node in ast.walk(fn):
        if not isinstance(node, (ast.If, ast.While, ast.IfExp, ast.Assert)):
            continue
        if node is not fn and node not in flow._parent:
            continue  # nested scope — its own checker owns it
        test = node.test
        for sub in ast.walk(test):
            if _is_guard_expr(sub):
                guards.append(flow.lift_to_await(sub))
    return guards


class _FnChecker:
    def __init__(self, rule, ctx, fn, stack):
        self.rule = rule
        self.ctx = ctx
        self.fn = fn
        self.symbol = enclosing_symbol(stack)
        self.flow = AwaitFlow(fn)
        self.guards = _collect_guards(fn, self.flow)
        self.findings: list = []

    def check(self):
        self._check_status_writes()
        self._check_attr_writes()
        return self.findings

    # -- half 1: unconditional status writers --------------------------------

    def _check_status_writes(self):
        for node in ast.walk(self.fn):
            if not (isinstance(node, ast.Call)
                    and _call_name(node.func) in STATUS_WRITERS):
                continue
            if self._in_nested_scope(node):
                continue
            write = self.flow.lift_to_await(node)
            guard = self._nearest_dominating_guard(write)
            if guard is None:
                continue  # unguarded entirely — AIL003's finding, not ours
            self._flag_if_stale(guard, write, node,
                                f"status write "
                                f"{_call_name(node.func)}()")

    # -- half 2: guarded state-attribute writes -------------------------------

    def _check_attr_writes(self):
        for node in ast.walk(self.fn):
            if not isinstance(node, ast.Assign):
                continue
            if self._in_nested_scope(node):
                continue
            for target in node.targets:
                chain = _attr_chain(target)
                if (chain is None
                        or not isinstance(target, ast.Attribute)
                        or target.attr not in STATE_ATTRS):
                    continue
                guard = self._nearest_chain_guard(chain, node)
                if guard is None:
                    continue
                self._flag_if_stale(guard, node, node,
                                    f"write to {chain}")

    # -- shared window check --------------------------------------------------

    def _flag_if_stale(self, guard: ast.AST, write: ast.AST,
                       report_at: ast.AST, what: str):
        between = self.flow.suspensions_between(guard, write)
        if not between:
            return
        last = max(between, key=lambda s: (getattr(s, "lineno", 0),
                                           getattr(s, "col_offset", 0)))
        if self._rechecked_after(last, write):
            return
        self.findings.append(self.ctx.finding(
            self.rule.rule_id, report_at,
            f"{what} acts on a guard read that {len(between)} suspension "
            f"point(s) ago (line {getattr(last, 'lineno', '?')}) may have "
            "invalidated — another task can complete/transition the state "
            "in that window (re-check the guard after the last await, or "
            "use an atomic conditional helper like update_status_if)",
            symbol=self.symbol))

    def _rechecked_after(self, last_suspension: ast.AST,
                         write: ast.AST) -> bool:
        """A guard evaluated at-or-after the last intervening suspension and
        before the write re-validates the read (the probe-after-await
        idiom: the probe IS that last suspension). The re-check must
        DOMINATE the write — a probe tucked inside a conditional branch
        leaves the branch-not-taken path acting on the stale read, and
        exists-path semantics say flag it."""
        from ..core import _pos
        lo, hi = _pos(last_suspension), _pos(write)
        for g in self.guards:
            if (lo <= _pos(g) < hi
                    and not self.flow.in_subtree(g, write)
                    and self.flow.dominates(g, write)):
                return True
        return False

    def _nearest_dominating_guard(self, write: ast.AST) -> ast.AST | None:
        from ..core import _pos
        best = None
        for g in self.guards:
            if self.flow.in_subtree(g, write):
                continue
            if self.flow.dominates(g, write):
                if best is None or _pos(g) > _pos(best):
                    best = g
        return best

    def _nearest_chain_guard(self, chain: str,
                             write: ast.AST) -> ast.AST | None:
        """Nearest dominating if/while test that READS the same attribute
        chain the write assigns."""
        from ..core import _pos
        best = None
        for node in ast.walk(self.fn):
            if not isinstance(node, (ast.If, ast.While)):
                continue
            if self._in_nested_scope(node):
                continue
            for sub in ast.walk(node.test):
                if (isinstance(sub, ast.Attribute)
                        and _attr_chain(sub) == chain):
                    anchor = self.flow.lift_to_await(sub)
                    if (not self.flow.in_subtree(anchor, write)
                            and self.flow.dominates(anchor, write)
                            and (best is None or _pos(anchor) > _pos(best))):
                        best = anchor
        return best

    def _in_nested_scope(self, node: ast.AST) -> bool:
        # AwaitFlow stops collecting at nested def/lambda boundaries, so a
        # node with no parent entry lives in a nested scope — the visitor
        # runs a separate checker for nested async defs.
        return node is not self.fn and node not in self.flow._parent


class _Visitor(ast.NodeVisitor):
    def __init__(self, rule, ctx):
        self.rule = rule
        self.ctx = ctx
        self.findings = []
        self._stack: list[ast.AST] = []

    def visit_ClassDef(self, node):
        self._stack.append(node)
        self.generic_visit(node)
        self._stack.pop()

    def visit_FunctionDef(self, node):
        self._stack.append(node)
        self.generic_visit(node)
        self._stack.pop()

    def visit_AsyncFunctionDef(self, node):
        self._stack.append(node)
        if node.name not in SHIM_NAMES:
            self.findings.extend(
                _FnChecker(self.rule, self.ctx, node, self._stack).check())
        self.generic_visit(node)
        self._stack.pop()


class StaleReadAcrossAwait(Rule):
    rule_id = "AIL007"
    name = "stale-read-across-await"
    description = ("a guard read of task/breaker state is invalidated by a "
                   "suspension point before the guarded write")

    def check_module(self, ctx):
        v = _Visitor(self, ctx)
        v.visit(ctx.tree)
        return v.findings
