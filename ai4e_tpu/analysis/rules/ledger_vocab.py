"""AIL011 — hop-ledger vocabulary drift between code and docs.

The bug class (AIL010's sibling on the EVENT-name surface): the ledger
vocabulary — the ``admitted``/``popped``/``h2d``/… event tokens every
``trace`` rendering, flight-recorder filter, and timeline export keys
on — grew by hand across PRs 8–11 with nothing keeping the operator
table in ``docs/observability.md`` honest. An event stamped in code but
absent from the table is a token the operator reading a trace cannot
interpret; a documented event nothing stamps is a filter that silently
matches nothing.

Three checks, run once over the whole project:

1. every event constant in ``observability/ledger.py`` (the UPPERCASE
   string-constant block) appears in the ``ai4e:ledger-vocabulary``
   marked table of ``docs/observability.md`` — and every backticked
   token in that table's first column is one of those constants;
2. the same, both directions, for the flight recorder's keep-reason
   constants (``REASON_*`` in ``observability/flight.py``) against the
   ``ai4e:flight-reasons`` marked table;
3. any LITERAL event name passed to ``ledger_event("…", …)`` or
   ``….stamp("…", …)`` anywhere in the project must be in the event
   vocabulary — a typo'd literal stamp otherwise mints an
   undocumented event that no table, filter, or renderer knows.

The doc tables are delimited by HTML-comment markers so prose mentions
of event words elsewhere in the doc never count::

    <!-- ai4e:ledger-vocabulary --> … <!-- /ai4e:ledger-vocabulary -->
    <!-- ai4e:flight-reasons -->    … <!-- /ai4e:flight-reasons -->

Tokens are the backticked words of each table row's FIRST cell (a row
may list several: ``| `h2d`, `compile` | … |``). Deleting the markers
does not defeat the rule: vocabulary in code with no marked region is
itself a finding.
"""

from __future__ import annotations

import ast
import os
import re

from ..core import Finding, ProjectRule

_DOC_FILE = os.path.join("docs", "observability.md")
_LEDGER_MOD = ("observability", "ledger.py")
_FLIGHT_MOD = ("observability", "flight.py")
_EVENT_MARK = "ai4e:ledger-vocabulary"
_REASON_MARK = "ai4e:flight-reasons"
_TOKEN_RE = re.compile(r"`([a-z][a-z0-9_]*)`")
_VALUE_RE = re.compile(r"^[a-z][a-z0-9_]*$")
_STAMP_FUNCS = ("ledger_event", "stamp")


def _module_is(module, tail: tuple[str, str]) -> bool:
    parts = module.path.replace(os.sep, "/").split("/")
    return len(parts) >= 2 and tuple(parts[-2:]) == tail


def _str_constants(module, name_filter) -> list[tuple[str, str, int]]:
    """(constant_name, value, line) for top-level ``NAME = "value"``
    assignments passing ``name_filter``."""
    out = []
    for node in module.tree.body:
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        target = node.targets[0]
        if not (isinstance(target, ast.Name) and name_filter(target.id)):
            continue
        if (isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, str)
                and _VALUE_RE.match(node.value.value)):
            out.append((target.id, node.value.value, node.lineno))
    return out


def _literal_stamps(module) -> list[tuple[str, int]]:
    """(event_literal, line) for ``ledger_event("x", …)`` /
    ``….stamp("x", …)`` calls with a literal first argument."""
    out = []
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Call) or not node.args:
            continue
        func = node.func
        name = (func.attr if isinstance(func, ast.Attribute)
                else func.id if isinstance(func, ast.Name) else None)
        if name not in _STAMP_FUNCS:
            continue
        arg = node.args[0]
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            out.append((arg.value, node.lineno))
    return out


class LedgerVocabularyDrift(ProjectRule):
    rule_id = "AIL011"
    name = "ledger-vocabulary-drift"
    description = ("every ledger event / flight keep-reason token in code "
                   "must appear in docs/observability.md's marked "
                   "vocabulary tables and vice versa; literal stamps must "
                   "use vocabulary events")

    def check_project(self, ctx):
        findings: list[Finding] = []
        events: dict[str, tuple[str, int]] = {}   # value -> (path, line)
        reasons: dict[str, tuple[str, int]] = {}
        stamps: list[tuple[str, str, int]] = []   # (value, path, line)
        for module in ctx.modules:
            if _module_is(module, _LEDGER_MOD):
                for _name, value, line in _str_constants(
                        module, str.isupper):
                    events.setdefault(value, (module.path, line))
            if _module_is(module, _FLIGHT_MOD):
                for _name, value, line in _str_constants(
                        module, lambda n: n.startswith("REASON_")):
                    reasons.setdefault(value, (module.path, line))
            for value, line in _literal_stamps(module):
                stamps.append((value, module.path, line))
        if not events and not reasons:
            return findings  # project carries no ledger vocabulary

        doc_path = _DOC_FILE.replace(os.sep, "/")
        doc_events = self._marked_tokens(ctx.root, _EVENT_MARK)
        doc_reasons = self._marked_tokens(ctx.root, _REASON_MARK)

        for vocab, doc, mark, kind in (
                (events, doc_events, _EVENT_MARK, "ledger event"),
                (reasons, doc_reasons, _REASON_MARK,
                 "flight keep-reason")):
            if not vocab:
                continue
            if doc is None:
                path, line = next(iter(vocab.values()))
                findings.append(Finding(
                    self.rule_id, path, line, 0,
                    f"code defines {kind} vocabulary but {doc_path} has "
                    f"no `<!-- {mark} -->` marked table — the operator "
                    "vocabulary table is missing or unmarked"))
                continue
            doc_set = {tok for tok, _loc in doc}
            for value, (path, line) in sorted(vocab.items()):
                if value not in doc_set:
                    findings.append(Finding(
                        self.rule_id, path, line, 0,
                        f"{kind} {value!r} is stamped/kept in code but "
                        f"absent from {doc_path}'s {mark} table — a "
                        "trace/flight consumer cannot interpret it"))
            for tok, (path, line) in sorted(doc):
                if tok not in vocab:
                    findings.append(Finding(
                        self.rule_id, path, line, 0,
                        f"{doc_path} documents {kind} {tok!r} but no "
                        "code defines it — stale row or a rename that "
                        "missed the docs"))

        for value, path, line in stamps:
            if value not in events:
                findings.append(Finding(
                    self.rule_id, path, line, 0,
                    f"literal ledger stamp {value!r} is not in the "
                    "observability/ledger.py vocabulary — use a "
                    "vocabulary constant (or add + document the event)"))
        return findings

    def _marked_tokens(self, root: str, mark: str
                       ) -> list[tuple[str, tuple[str, int]]] | None:
        """Backticked tokens from the FIRST table cell of each row
        inside the ``mark`` region, or None when the region is absent.
        Duplicate tokens keep their first location."""
        path = os.path.join(root, _DOC_FILE)
        rel = _DOC_FILE.replace(os.sep, "/")
        try:
            with open(path, encoding="utf-8") as fh:
                lines = fh.read().splitlines()
        except OSError:
            return None
        inside = False
        found_region = False
        out: list[tuple[str, tuple[str, int]]] = []
        seen: set[str] = set()
        for i, line in enumerate(lines, 1):
            # Markers may carry an annotation: `<!-- mark — why -->`.
            if f"<!-- /{mark}" in line:
                inside = False
                continue
            if f"<!-- {mark}" in line:
                inside, found_region = True, True
                continue
            if not inside or not line.lstrip().startswith("|"):
                continue
            cells = line.split("|")
            first = cells[1] if len(cells) > 1 else ""
            for m in _TOKEN_RE.finditer(first):
                tok = m.group(1)
                if tok not in seen:
                    seen.add(tok)
                    out.append((tok, (rel, i)))
        return out if found_region else None
