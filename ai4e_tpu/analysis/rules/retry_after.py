"""AIL015 — refusal without Retry-After.

The bug class: a 429/503 is the platform telling a caller "not now, try
again" — and every refusal surface the platform ships has a caller that
OBEYS retry metadata: the dispatcher's backpressure redelivery derives
its delay from ``Retry-After`` (``broker/dispatcher.py``), the tenant
quota edge composes the token bucket's drain time into it
(``tenancy/``), and the shedder's contract since PR 9 is "every 503
carries the cost of coming back". A refusal WITHOUT the header degrades
each of those callers to blind exponential guessing — the retry storm
arrives exactly when the platform is least able to absorb it. PR 18's
drain path raises the stakes: a draining worker's 503 is an explicit
"retry a peer NOW", and a missing header there turns an orderly rollout
into visible latency.

The rule flags ``web.Response``/``web.json_response`` (and bare
``Response``/``json_response``) calls whose ``status=`` is the literal
429 or 503 when the ``headers=`` argument is absent or is a dict literal
with no ``Retry-After`` key (case-insensitive). Scope is the code that
answers callers over HTTP — ``gateway/``, ``rig/``, and the worker's
serving surface (``runtime/worker.py``) — matching the ISSUE's refusal
inventory; non-literal ``headers=`` values are accepted (the mapping was
built elsewhere — the rule polices the idiom, not the dataflow).
Deliberate exceptions (e.g. rotate markers whose callers rotate instead
of waiting) carry ``# ai4e: noqa[AIL015]`` with the reason.
"""

from __future__ import annotations

import ast

from ..core import Rule, enclosing_symbol

#: Response constructors whose kwargs carry the refusal.
RESPONSE_CALLS = frozenset({"Response", "json_response"})
#: Statuses that mean "come back later" — and so must say when.
RETRYABLE_STATUSES = frozenset({429, 503})


def _status_of(node: ast.Call) -> int | None:
    for kw in node.keywords:
        if (kw.arg == "status" and isinstance(kw.value, ast.Constant)
                and isinstance(kw.value.value, int)):
            return kw.value.value
    return None


def _headers_carry_retry_after(node: ast.Call) -> bool:
    """True when headers= visibly carries Retry-After OR is dynamic
    (built elsewhere — not this rule's business)."""
    for kw in node.keywords:
        if kw.arg != "headers":
            continue
        value = kw.value
        if not isinstance(value, ast.Dict):
            return True  # dynamic mapping — accepted
        for key in value.keys:
            if key is None:
                return True  # **spread — accepted (dynamic)
            if (isinstance(key, ast.Constant)
                    and isinstance(key.value, str)
                    and key.value.lower() == "retry-after"):
                return True
        return False
    return False  # no headers= at all


def _in_scope(path: str) -> bool:
    return ("gateway/" in path or "rig/" in path
            or path.endswith("runtime/worker.py"))


class RefusalWithoutRetryAfter(Rule):
    rule_id = "AIL015"
    name = "refusal-without-retry-after"
    description = ("429/503 refusals on the gateway/worker/rig HTTP "
                   "surfaces must carry Retry-After — a refusal without "
                   "retry metadata turns every well-behaved caller into "
                   "a blind retry storm")

    def check_module(self, ctx):
        if not _in_scope(ctx.path):
            return []
        rule = self

        class _Visitor(ast.NodeVisitor):
            def __init__(self):
                self.findings = []
                self._stack: list[ast.AST] = []

            def _enter(self, node):
                self._stack.append(node)
                self.generic_visit(node)
                self._stack.pop()

            visit_ClassDef = _enter
            visit_FunctionDef = _enter
            visit_AsyncFunctionDef = _enter

            def visit_Call(self, node):
                fn = node.func
                name = fn.attr if isinstance(fn, ast.Attribute) else (
                    fn.id if isinstance(fn, ast.Name) else "")
                if name in RESPONSE_CALLS:
                    status = _status_of(node)
                    if (status in RETRYABLE_STATUSES
                            and not _headers_carry_retry_after(node)):
                        self.findings.append(ctx.finding(
                            rule.rule_id, node,
                            f"{status} refusal without Retry-After — "
                            "callers (dispatcher backpressure, quota-"
                            "aware clients) derive their retry delay "
                            "from it; add headers={'Retry-After': ...} "
                            "or justify why this caller must not wait",
                            symbol=enclosing_symbol(self._stack)))
                self.generic_visit(node)

        visitor = _Visitor()
        visitor.visit(ctx.tree)
        return visitor.findings
