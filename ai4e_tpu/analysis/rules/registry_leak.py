"""AIL002 — metrics created on ``DEFAULT_REGISTRY`` despite an injected one.

The bug class (the DispatcherPool bug fixed by hand in PR 3): a component
accepts a ``metrics=``/``registry=`` parameter — the assembly plumbs its
own ``MetricsRegistry`` through it — but some method creates or
increments a series on the process-global ``DEFAULT_REGISTRY`` anyway.
Nothing crashes; the series just silently lands in a registry nobody
scrapes, and the counter is "missing" in the assembly's ``/metrics``.

The ONE blessed default-resolution idiom is ``<param> or DEFAULT_REGISTRY``
(what every component in the codebase uses). Anything else that routes a
metric call at ``DEFAULT_REGISTRY`` inside such a class is flagged:

- ``DEFAULT_REGISTRY.counter(...)`` directly in a method;
- ``local = DEFAULT_REGISTRY`` (including the conditional
  ``if metrics is None: metrics = DEFAULT_REGISTRY`` rebinding — the
  form the replication/tracing leaks hid in) followed by a metric call
  through the local;
- ``self.metrics = DEFAULT_REGISTRY`` pinning the attribute to the
  global despite the injectable parameter.
"""

from __future__ import annotations

import ast

from ..core import Rule

INJECT_PARAMS = frozenset({"metrics", "registry"})
METRIC_METHODS = frozenset({"counter", "gauge", "histogram",
                            "inc", "dec", "set", "observe"})


def _is_default_registry(node: ast.AST) -> bool:
    """Name/attribute chain ending in DEFAULT_REGISTRY."""
    if isinstance(node, ast.Name):
        return node.id == "DEFAULT_REGISTRY"
    if isinstance(node, ast.Attribute):
        return node.attr == "DEFAULT_REGISTRY"
    return False


def _ordered(node: ast.AST):
    """Pre-order DFS — source order, which taint tracking needs (ast.walk
    is breadth-first and would visit a later call before an earlier nested
    assignment)."""
    for child in ast.iter_child_nodes(node):
        yield child
        yield from _ordered(child)


class MetricsRegistryLeak(Rule):
    rule_id = "AIL002"
    name = "metrics-registry-leak"
    description = ("class accepts a metrics=/registry= parameter but routes "
                   "metric calls at DEFAULT_REGISTRY")

    def check_module(self, ctx):
        findings = []
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ClassDef):
                findings.extend(self._check_class(ctx, node))
        return findings

    def _check_class(self, ctx, cls: ast.ClassDef):
        methods = [n for n in cls.body
                   if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]
        injected: set[str] = set()
        for m in methods:
            args = m.args
            for a in (args.posonlyargs + args.args + args.kwonlyargs):
                if a.arg in INJECT_PARAMS:
                    injected.add(a.arg)
        if not injected:
            return
        params = frozenset(injected)
        for m in methods:
            yield from self._check_method(ctx, cls, m, params)

    def _check_method(self, ctx, cls, method, params: frozenset[str]):
        symbol = f"{cls.name}.{method.name}"
        tainted: set[str] = set()
        for node in _ordered(method):
            if isinstance(node, ast.Assign):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        if _is_default_registry(node.value):
                            # e.g. `if metrics is None: metrics =
                            # DEFAULT_REGISTRY` — the conditional rebinding
                            # the leak hides in. Taint; the metric call
                            # through it is the finding. Any other value —
                            # notably the blessed `metrics or
                            # DEFAULT_REGISTRY` BoolOp — clears it.
                            tainted.add(tgt.id)
                        else:
                            tainted.discard(tgt.id)
                    elif (isinstance(tgt, ast.Attribute)
                          and _is_default_registry(node.value)):
                        yield ctx.finding(
                            self.rule_id, node,
                            f"{cls.name} accepts "
                            f"{'/'.join(sorted(params))}= but pins "
                            f"{ast.unparse(tgt)} to DEFAULT_REGISTRY — use "
                            "the injected registry "
                            "(`metrics or DEFAULT_REGISTRY`)",
                            symbol=symbol)
            elif isinstance(node, ast.Call):
                func = node.func
                if not (isinstance(func, ast.Attribute)
                        and func.attr in METRIC_METHODS):
                    continue
                target = func.value
                direct = _is_default_registry(target)
                via_taint = (isinstance(target, ast.Name)
                             and target.id in tainted)
                if direct or via_taint:
                    what = ("DEFAULT_REGISTRY" if direct else
                            f"{target.id} (rebound to DEFAULT_REGISTRY)")
                    yield ctx.finding(
                        self.rule_id, node,
                        f"{cls.name} accepts "
                        f"{'/'.join(sorted(params))}= but calls "
                        f".{func.attr}() on {what} — series lands in the "
                        "process-global registry, invisible to the "
                        "assembly's /metrics (blessed default: "
                        "`metrics or DEFAULT_REGISTRY`)",
                        symbol=symbol)
