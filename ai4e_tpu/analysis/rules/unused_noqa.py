"""AIL019 — unused suppression (the ruff-RUF100 shape).

An ``# ai4e: noqa[AILxxx]`` on a line where that rule no longer fires is
not harmless cruft: the bug it blessed was fixed, the blindfold stayed
on, and the NEXT regression on that line lands pre-suppressed. The check
itself lives in ``core.Analyzer.run`` — it needs the complete raw
finding set, which no individual rule sees — but the id is registered
here as a normal catalog rule so ``--select``/``--ignore``, the rule
count gate in scripts/lint.sh, and the docs catalog treat it uniformly.

Scope guard: only rules ACTIVE in the run are judged. Under ``--select
AIL001`` a ``noqa[AIL005]`` is unproven (AIL005 never ran), not unused.
A justified keep is expressed by adding AIL019 to the same marker:
``# ai4e: noqa[AIL005,AIL019] — fires only under the py3.12 parser``.
"""

from __future__ import annotations

from ..core import Rule


class UnusedSuppression(Rule):
    rule_id = "AIL019"
    name = "unused-suppression"
    description = ("an `ai4e: noqa[RULE]` comment on a line where RULE "
                  "does not fire suppresses nothing today and the next "
                  "real finding tomorrow — drop it")
    family = "hygiene"

    def check_module(self, ctx):
        # Implemented in Analyzer.run (needs the whole raw finding set).
        return ()
