"""AIL016–AIL018 — cross-process wire-contract drift.

The platform's hardest review-found bugs were wire-shaped: the PR 8
backend-vs-published route-label split (two processes disagreeing about
what a path is called, pinning goodput SLOs bad during shedding), and
PR 18's reload-409-while-draining interlock that every reload caller
must branch on or silently wedge an upgrade. AIL001–AIL015 verify
invariants *within* a process; these three check the contracts *between*
them, against the statically extracted HTTP surface
(``analysis/wire_surface.py``):

- **AIL016 client-route-drift** — a client call whose path+method
  resolves to no registered route (it can only 404), and a registered
  route that no client calls and no ``external`` caller row in
  docs/API.md's ``ai4e:routes`` table vouches for (dead surface). The
  marked table is also kept honest both directions, AIL011-style:
  a registered route missing from the table, and a table row nothing
  registers, are both findings.
- **AIL017 header-vocabulary-drift** — the ``X-*``/``Retry-After``
  header vocabulary must round-trip: every header code emits needs a
  reader somewhere (or an ``external`` reader documented), every header
  code reads needs an emitter (or an ``external`` emitter — browsers
  and load clients set ``X-Deadline-Ms``), every used header needs a
  row in the ``ai4e:headers`` marked table, and every documented header
  must still exist in code. A literal header outside the vocabulary is
  a typo-minted header no peer will ever read.
- **AIL018 unhandled-refusal-status** — a distinguished refusal status
  a route demonstrably mints (409 drain/ownership interlock, 429
  quota/shed, 503 backpressure/standby, 504 deadline) that the calling
  function's branch structure never distinguishes from generic failure.
  Callers that hand the raw response back to *their* caller (``_request``
  helpers) are exempt — the distinguishing happens one frame up.

Wire findings carry a ``fingerprint_key`` naming the CONTRACT (method +
canonical path, or header name), not the file/line — moving a
registration between modules is a refactor, not a contract change, and
must not churn the baseline.

The out-of-tree client library (``clients/python/``) is parsed as
client-side evidence only: its calls count as callers and its header
uses as emitters/readers, but it registers no routes.
"""

from __future__ import annotations

import os
import re

from ..core import Finding, ProjectRule, parse_module
from ..wire_surface import (
    RouteReg,
    WireSurface,
    extract_wire_surface,
    load_extra_clients,
    parse_shape,
    shape_display,
)

_API_DOC = "docs/API.md"
ROUTES_MARK = "ai4e:routes"
HEADERS_MARK = "ai4e:headers"

_METHOD_RE = re.compile(r"`([A-Z*]+)`")
_PATH_RE = re.compile(r"`(/[^`]*)`")
_HEADER_TOKEN_RE = re.compile(r"`([A-Za-z][A-Za-z0-9-]*)`")

#: Operator-facing names for the distinguished refusal statuses.
STATUS_LABELS = {
    409: "conflict — drain/ownership interlock",
    429: "quota/shed refusal",
    503: "backpressure/standby refusal",
    504: "deadline exceeded",
}


def _safe_parse(abspath: str, rel: str):
    try:
        return parse_module(abspath, rel)
    except (OSError, SyntaxError, ValueError):
        return None


def surface_of(ctx) -> WireSurface:
    """Extract (once per ProjectContext — the three wire rules share one
    pass) the project's wire surface, with ``clients/python/`` parsed in
    as extra client-side evidence."""
    cached = getattr(ctx, "_wire_surface", None)
    if cached is None:
        extra = load_extra_clients(ctx.root, _safe_parse)
        cached = extract_wire_surface(ctx, extra)
        ctx._wire_surface = cached
    return cached


def marked_rows(root: str, mark: str
                ) -> list[tuple[list[str], int]] | None:
    """(cells, line) for each data row of the ``mark`` marked table in
    docs/API.md, or None when the region is absent. Separator rows and
    the header row (no backticked first cell) are skipped."""
    path = os.path.join(root, *_API_DOC.split("/"))
    try:
        with open(path, encoding="utf-8") as fh:
            lines = fh.read().splitlines()
    except OSError:
        return None
    inside = found = False
    out: list[tuple[list[str], int]] = []
    for i, line in enumerate(lines, 1):
        if f"<!-- /{mark}" in line:
            inside = False
            continue
        if f"<!-- {mark}" in line:
            inside = found = True
            continue
        if not inside:
            continue
        s = line.strip()
        if not s.startswith("|"):
            continue
        cells = [c.strip() for c in s.strip("|").split("|")]
        if not cells or "`" not in cells[0]:
            continue  # header or separator row
        out.append((cells, i))
    return out if found else None


def _first(uses):
    return min(uses, key=lambda u: (u.path, u.line))


class ClientRouteDrift(ProjectRule):
    rule_id = "AIL016"
    name = "client-route-drift"
    description = ("every client call site must resolve to a registered "
                  "route and every registered route must have a caller "
                  "(in code, or documented `external` in docs/API.md's "
                  "ai4e:routes table); the table round-trips with the "
                  "registrations both directions")
    family = "wire"

    def check_project(self, ctx):
        findings: list[Finding] = []
        surface = surface_of(ctx)
        routes = surface.matchable_routes()
        if not routes and not surface.clients:
            return findings

        by_key: dict[tuple, list[RouteReg]] = {}
        for r in routes:
            by_key.setdefault(r.key, []).append(r)

        rows = marked_rows(ctx.root, ROUTES_MARK)
        doc_keys: dict[tuple, tuple[str, int]] = {}  # key -> (callers, line)
        if rows is not None:
            for cells, line in rows:
                m = _METHOD_RE.search(cells[0]) if cells else None
                p = _PATH_RE.search(cells[1]) if len(cells) > 1 else None
                if not m or not p:
                    continue
                callers = cells[3] if len(cells) > 3 else ""
                doc_keys[(m.group(1), parse_shape(p.group(1)))] = (
                    callers, line)
        elif routes:
            r0 = min(routes, key=lambda r: (r.path, r.line))
            findings.append(Finding(
                self.rule_id, r0.path, r0.line, 0,
                f"project registers HTTP routes but {_API_DOC} has no "
                f"`<!-- {ROUTES_MARK} -->` marked table — generate one "
                "with `python -m ai4e_tpu.analysis --dump-wire`",
                snippet="", fingerprint_key=f"{self.rule_id}|no-table"))

        # Direction 1: client call with no matching registration.
        flagged_client: set[tuple[str, tuple]] = set()
        for ref in surface.clients:
            if surface.routes_for(ref):
                continue
            ck = (ref.method, ref.shape)
            if ck in flagged_client:
                continue
            flagged_client.add(ck)
            findings.append(Finding(
                self.rule_id, ref.path, ref.line, 0,
                f"client calls {ref.method} {ref.display} but no "
                "registered route matches — the request can only 404 "
                "(the PR 8 route-label split began as exactly this "
                "drift)", symbol=ref.symbol,
                fingerprint_key=(f"{self.rule_id}|client|"
                                 f"{ref.method} {ref.display}")))

        # Direction 2: registration with no caller; doc round-trip.
        for key in sorted(by_key, key=lambda k: (k[0], k[1])):
            regs = by_key[key]
            r0 = min(regs, key=lambda r: (r.path, r.line))
            doc = doc_keys.get(key)
            if rows is not None and doc is None:
                findings.append(Finding(
                    self.rule_id, r0.path, r0.line, 0,
                    f"route {r0.method} {r0.display} is registered but "
                    f"absent from {_API_DOC}'s {ROUTES_MARK} table — "
                    "regenerate it with --dump-wire",
                    fingerprint_key=(f"{self.rule_id}|undocumented|"
                                     f"{r0.method} {r0.display}")))
            # Only an explicit `external` caller note counts as doc
            # evidence: module names in the Callers cell are derived
            # from code and must be backed by a live call site.
            external = doc is not None and "external" in doc[0].lower()
            if not surface.clients_for(r0) and not external:
                findings.append(Finding(
                    self.rule_id, r0.path, r0.line, 0,
                    f"route {r0.method} {r0.display} has no client call "
                    "site in the platform and no `external` caller "
                    f"documented in {_API_DOC}'s {ROUTES_MARK} table — "
                    "dead surface, or a caller this analyzer cannot see "
                    "(document it as external)",
                    fingerprint_key=(f"{self.rule_id}|dead-route|"
                                     f"{r0.method} {r0.display}")))
        for key in sorted(doc_keys, key=lambda k: (k[0], k[1])):
            if key not in by_key:
                _callers, line = doc_keys[key]
                method, shape = key
                findings.append(Finding(
                    self.rule_id, _API_DOC, line, 0,
                    f"{_API_DOC} documents route {method} "
                    f"{shape_display(shape)} but nothing registers it — "
                    "stale row (regenerate with --dump-wire)",
                    fingerprint_key=(f"{self.rule_id}|stale-doc|"
                                     f"{method} {shape_display(shape)}")))
        return findings


class HeaderVocabularyDrift(ProjectRule):
    rule_id = "AIL017"
    name = "header-vocabulary-drift"
    description = ("every emitted X-*/Retry-After header needs a reader "
                  "and a row in docs/API.md's ai4e:headers table (and "
                  "vice versa); a literal header outside the vocabulary "
                  "is typo-minted")
    family = "wire"

    def check_project(self, ctx):
        findings: list[Finding] = []
        surface = surface_of(ctx)
        emits: dict[str, list] = {}
        reads: dict[str, list] = {}
        for use in surface.headers:
            if use.kind == "emit":
                emits.setdefault(use.name, []).append(use)
            elif use.kind == "read":
                reads.setdefault(use.name, []).append(use)
        used = set(emits) | set(reads)
        if not used:
            return findings

        rows = marked_rows(ctx.root, HEADERS_MARK)
        if rows is None:
            u0 = _first([u for n in used for u in emits.get(n, [])
                         + reads.get(n, [])])
            findings.append(Finding(
                self.rule_id, u0.path, u0.line, 0,
                f"project uses wire headers but {_API_DOC} has no "
                f"`<!-- {HEADERS_MARK} -->` marked table — generate one "
                "with `python -m ai4e_tpu.analysis --dump-wire`",
                fingerprint_key=f"{self.rule_id}|no-table"))
            return findings

        doc: dict[str, tuple[str, str, int]] = {}  # name -> (emit, read, ln)
        for cells, line in rows:
            m = _HEADER_TOKEN_RE.search(cells[0]) if cells else None
            if not m:
                continue
            doc[m.group(1)] = (cells[1] if len(cells) > 1 else "",
                               cells[2] if len(cells) > 2 else "", line)

        for name in sorted(used):
            if name not in doc:
                u0 = _first(emits.get(name, []) + reads.get(name, []))
                findings.append(Finding(
                    self.rule_id, u0.path, u0.line, 0,
                    f"header {name!r} is not in {_API_DOC}'s "
                    f"{HEADERS_MARK} vocabulary — typo-minted (no peer "
                    "will ever read a misspelled header) or undocumented",
                    fingerprint_key=f"{self.rule_id}|vocab|{name}"))
        for name in sorted(emits):
            if name in reads:
                continue
            read_cell = doc.get(name, ("", "", 0))[1]
            if "external" in read_cell.lower():
                continue
            u0 = _first(emits[name])
            findings.append(Finding(
                self.rule_id, u0.path, u0.line, 0,
                f"header {name!r} is emitted but nothing in the platform "
                "reads it and no `external` reader is documented in "
                f"{_API_DOC} — dead bytes on every response, or a "
                "reader that drifted away",
                fingerprint_key=f"{self.rule_id}|emit-no-reader|{name}"))
        for name in sorted(reads):
            if name in emits:
                continue
            emit_cell = doc.get(name, ("", "", 0))[0]
            if "external" in emit_cell.lower():
                continue
            u0 = _first(reads[name])
            findings.append(Finding(
                self.rule_id, u0.path, u0.line, 0,
                f"header {name!r} is read but nothing emits it and no "
                f"`external` emitter is documented in {_API_DOC} — the "
                "branch it guards is dead",
                fingerprint_key=f"{self.rule_id}|read-no-emitter|{name}"))
        for name in sorted(doc):
            if name not in used:
                findings.append(Finding(
                    self.rule_id, _API_DOC, doc[name][2], 0,
                    f"{_API_DOC} documents header {name!r} but no code "
                    "emits or reads it — stale row (regenerate with "
                    "--dump-wire)",
                    fingerprint_key=f"{self.rule_id}|stale-doc|{name}"))
        return findings


class UnhandledRefusalStatus(ProjectRule):
    rule_id = "AIL018"
    name = "unhandled-refusal-status"
    description = ("a refusal status a route demonstrably returns (409 "
                  "drain interlock, 429 shed, 503 backpressure, 504 "
                  "deadline) that the caller never distinguishes from "
                  "generic failure — the PR 18 reload-409 class")
    family = "wire"

    def check_project(self, ctx):
        findings: list[Finding] = []
        surface = surface_of(ctx)
        seen: set[tuple] = set()
        for ref in surface.clients:
            if ref.propagates:
                continue  # raw response handed up — caller distinguishes
            statuses: set[int] = set()
            for route in surface.routes_for(ref):
                statuses |= route.statuses
            for status in sorted(statuses - set(ref.handled)):
                key = (ref.method, ref.shape, status, ref.symbol)
                if key in seen:
                    continue
                seen.add(key)
                findings.append(Finding(
                    self.rule_id, ref.path, ref.line, 0,
                    f"{ref.method} {ref.display} can return {status} "
                    f"({STATUS_LABELS.get(status, 'refusal')}) but "
                    f"{ref.symbol or 'this call site'} never branches on "
                    "it — generic-failure handling here wedges the "
                    "refusal contract (reload-409 class)",
                    symbol=ref.symbol,
                    fingerprint_key=(f"{self.rule_id}|{ref.method} "
                                     f"{ref.display}|{status}|"
                                     f"{ref.symbol}")))
        return findings


def _route_rows(surface: WireSurface) -> list[tuple[str, str, str, str]]:
    """(method, display, registered-in, callers) rows, deduped by wire
    key, for the generated ai4e:routes table."""
    by_key: dict[tuple, list[RouteReg]] = {}
    for r in surface.matchable_routes():
        by_key.setdefault(r.key, []).append(r)
    rows = []
    for key in sorted(by_key, key=lambda k: (k[1], k[0])):
        regs = sorted(by_key[key], key=lambda r: (r.path, r.line))
        r0 = regs[0]
        reg_cell = ", ".join(
            f"`{p}`" for p in dict.fromkeys(r.path for r in regs))
        callers = sorted({c.path for c in surface.clients_for(r0)})
        caller_cell = ", ".join(f"`{p}`" for p in callers) if callers else "—"
        rows.append((f"`{r0.method}`", f"`{r0.display}`", reg_cell,
                     caller_cell))
    return rows


def _header_rows(surface: WireSurface) -> list[tuple[str, str, str]]:
    """(header, emitted-by, read-by) rows for the generated
    ai4e:headers table. Mention-only headers are excluded — a strip
    list or constant alone creates no wire obligation."""
    emits: dict[str, set[str]] = {}
    reads: dict[str, set[str]] = {}
    for use in surface.headers:
        if use.kind == "emit":
            emits.setdefault(use.name, set()).add(use.path)
        elif use.kind == "read":
            reads.setdefault(use.name, set()).add(use.path)
    rows = []
    for name in sorted(set(emits) | set(reads)):
        e = ", ".join(f"`{p}`" for p in sorted(emits.get(name, ()))) or "—"
        r = ", ".join(f"`{p}`" for p in sorted(reads.get(name, ()))) or "—"
        rows.append((f"`{name}`", e, r))
    return rows


def dump_wire(root: str, ctx) -> str:
    """Render the two marked tables for docs/API.md (the --dump-wire
    helper). Humans edit `—` cells to `external — <who>` for callers or
    peers the analyzer cannot see; those notes are preserved manually on
    regeneration (the tool prints, it does not rewrite the doc)."""
    surface = surface_of(ctx)
    out = [f"<!-- {ROUTES_MARK} -->",
           "| Method | Path | Registered in | Callers |",
           "|---|---|---|---|"]
    out += ["| " + " | ".join(row) + " |" for row in _route_rows(surface)]
    out += [f"<!-- /{ROUTES_MARK} -->", "",
            f"<!-- {HEADERS_MARK} -->",
            "| Header | Emitted by | Read by |",
            "|---|---|---|"]
    out += ["| " + " | ".join(row) + " |" for row in _header_rows(surface)]
    out += [f"<!-- /{HEADERS_MARK} -->"]
    return "\n".join(out) + "\n"
