"""Rule registry — one module per rule, ids are append-only stable."""

from .balance import (
    JournalReplayRoundTrip,
    PairSpecDrift,
    UnbalancedPairedEffect,
)
from .blocking import BlockingCallInAsync
from .bucket_literal import StaticBucketLadder
from .config_drift import ConfigDrift
from .fire_and_forget import FireAndForgetTask
from .ledger_vocab import LedgerVocabularyDrift
from .lock_await import LockAcrossSlowAwait
from .metric_label import UnboundedMetricLabel
from .metrics_drift import MetricsDrift
from .registry_leak import MetricsRegistryLeak
from .retry_after import RefusalWithoutRetryAfter
from .rmw import NonatomicReadModifyWrite
from .stale_read import StaleReadAcrossAwait
from .status_clobber import TerminalStatusClobber
from .swallowed import SwallowedException
from .unplaced import UnplacedDeviceTransfer
from .unused_noqa import UnusedSuppression
from .wire import ClientRouteDrift, HeaderVocabularyDrift, UnhandledRefusalStatus

ALL_RULES = [
    BlockingCallInAsync,
    MetricsRegistryLeak,
    TerminalStatusClobber,
    FireAndForgetTask,
    SwallowedException,
    ConfigDrift,
    StaleReadAcrossAwait,
    LockAcrossSlowAwait,
    NonatomicReadModifyWrite,
    MetricsDrift,
    LedgerVocabularyDrift,
    StaticBucketLadder,
    UnboundedMetricLabel,
    UnplacedDeviceTransfer,
    RefusalWithoutRetryAfter,
    ClientRouteDrift,
    HeaderVocabularyDrift,
    UnhandledRefusalStatus,
    UnbalancedPairedEffect,
    JournalReplayRoundTrip,
    PairSpecDrift,
    UnusedSuppression,
]

__all__ = ["ALL_RULES"] + [cls.__name__ for cls in ALL_RULES]
