"""AIL003 — task-status write without a ``TaskStatus.TERMINAL`` re-check.

The bug class (the PR 3 double-completion, caught live by the chaos
harness): a delivery path writes task status unconditionally — e.g. the
"Awaiting service availability" backpressure write — on a message that
can be a REDELIVERY of a task that already completed. The write clobbers
the terminal status back to a live one, the redelivery then completes the
task a second time, and the client observes two completions (the exact
invariant ``chaos/invariants.py`` rejects).

The rule: any status-writing call (``update_task_status`` /
``update_status`` / ``complete_task`` / ``fail_task`` / ``_try_update``)
must sit in a function that visibly re-checks terminality, meaning the
function either

- tests membership against ``TaskStatus.TERMINAL`` (``... in`` /
  ``not in``), or
- calls one of the blessed guard helpers the task store exports —
  ``update_status_if`` / ``requeue_if`` (atomic conditional transitions),
  ``_suppress_duplicate``, or the shared ``TaskManagerBase.is_terminal``
  probe — or
- is itself registered through ``api_async_func`` (the service shell
  re-checks terminality before invoking the handler — the shell is the
  guard).

Exemptions: modules under ``taskstore/`` (the guard layer itself — the
store's writers are the primitives the helpers are built FROM), and
functions that are themselves thin writer shims (``_try_update`` etc.) —
their CALLERS are where the decision is made and checked.
"""

from __future__ import annotations

import ast

from ..core import Rule, enclosing_symbol

WRITER_CALLS = frozenset({
    "update_task_status", "update_status", "complete_task", "fail_task",
    "_try_update",
})
# Functions that ARE the write plumbing: wrappers whose only job is to
# forward/guard the raw call. Flagging inside them would double-report
# every call site.
SHIM_NAMES = WRITER_CALLS | frozenset({"_update"})
GUARD_HELPERS = frozenset({"update_status_if", "requeue_if",
                           "_suppress_duplicate", "is_terminal"})
GUARD_DECORATORS = ("api_async_func",)
EXEMPT_PATH_PARTS = ("taskstore/",)


def _call_name(func: ast.AST) -> str | None:
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


def _has_terminal_check(fn: ast.AST) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.Compare):
            for op, comparator in zip(node.ops, node.comparators):
                if isinstance(op, (ast.In, ast.NotIn)):
                    if any(isinstance(n, ast.Attribute)
                           and n.attr == "TERMINAL"
                           for n in ast.walk(comparator)):
                        return True
        elif isinstance(node, ast.Call):
            name = _call_name(node.func)
            if name in GUARD_HELPERS:
                return True
    return False


def _shell_guarded(fn: ast.AST) -> bool:
    for dec in getattr(fn, "decorator_list", []):
        target = dec.func if isinstance(dec, ast.Call) else dec
        name = _call_name(target)
        if name in GUARD_DECORATORS:
            return True
    return False


class _Visitor(ast.NodeVisitor):
    def __init__(self, rule, ctx):
        self.rule = rule
        self.ctx = ctx
        self.findings = []
        self._stack: list[ast.AST] = []
        # Per-function cached guard verdict, keyed by id(node).
        self._guarded: dict[int, bool] = {}

    def _enter(self, node):
        self._stack.append(node)
        self.generic_visit(node)
        self._stack.pop()

    visit_ClassDef = _enter
    visit_FunctionDef = _enter
    visit_AsyncFunctionDef = _enter

    def _enclosing_fn(self):
        for node in reversed(self._stack):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return node
        return None

    def visit_Call(self, node):
        name = _call_name(node.func)
        if name in WRITER_CALLS:
            fn = self._enclosing_fn()
            if fn is None:
                self._flag(node, name, "<module>")
            elif fn.name not in SHIM_NAMES:
                key = id(fn)
                if key not in self._guarded:
                    # Shell-guard exemption walks the WHOLE enclosing
                    # stack: a progress callback nested inside an
                    # api_async_func handler is only ever invoked from
                    # that (shell-guarded) execution.
                    self._guarded[key] = (_has_terminal_check(fn)
                                          or any(_shell_guarded(f)
                                                 for f in self._stack))
                if not self._guarded[key]:
                    self._flag(node, name, fn.name)
        self.generic_visit(node)

    def _flag(self, node, name, fn_name):
        self.findings.append(self.ctx.finding(
            self.rule.rule_id, node,
            f"status write {name}() in {fn_name!r} without a "
            "TaskStatus.TERMINAL re-check — a redelivery can clobber a "
            "completed task back to live and double-complete it (guard "
            "with `canonical in TaskStatus.TERMINAL`, update_status_if, "
            "or _suppress_duplicate)",
            symbol=enclosing_symbol(self._stack)))


class TerminalStatusClobber(Rule):
    rule_id = "AIL003"
    name = "terminal-status-clobber"
    description = ("task-status writes must re-check TaskStatus.TERMINAL "
                   "(or go through a blessed conditional helper)")

    def check_module(self, ctx):
        if any(part in ctx.path for part in EXEMPT_PATH_PARTS):
            return []
        v = _Visitor(self, ctx)
        v.visit(ctx.tree)
        return v.findings
