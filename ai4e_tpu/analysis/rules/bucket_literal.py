"""AIL012 — static bucket/tile ladder literal outside the deriver module.

The bug class: PR 13 made the batch-bucket ladder a live artifact derived
from the request-shape histogram (``runtime/ladder.py``), replacing the
hard-coded ``(1, 2, 4, ..., 256)`` tuple that had pinned the device path
to a traffic guess since the seed. A new literal ladder pasted anywhere
under ``runtime/`` — a "temporary" default in a family factory, a copy
of the exposition buckets in the batcher — silently reintroduces exactly
that static guess, and nothing at runtime would notice: the code works,
the ladder just stops following traffic. The factory defaults that must
exist live as named constants in the deriver module, the one place this
rule does not scan.

A bucket/tile ladder literal is recognized as: a tuple or list whose
LEADING elements are >= 3 integer constants, strictly ascending,
starting at 1 (every ladder admits single-example batches; shape tuples
and stage-size tuples fail the ascending-from-1 test). Trailing
non-integer elements (e.g. ``float("inf")`` exposition sentinels) do not
exempt the literal — the pre-PR-13 exposition tuple ended in exactly
such a sentinel.
"""

from __future__ import annotations

import ast

from ..core import Rule, enclosing_symbol

#: Only the serving runtime is in scope — model configs, benches, and
#: tests legitimately write explicit ladders.
SCOPE_PART = "runtime/"
#: The deriver module: the single home for factory-default ladders.
EXEMPT_SUFFIX = "runtime/ladder.py"
MIN_RUN = 3


def _leading_ints(node) -> list[int]:
    out: list[int] = []
    for elt in node.elts:
        if (isinstance(elt, ast.Constant) and isinstance(elt.value, int)
                and not isinstance(elt.value, bool)):
            out.append(elt.value)
        else:
            break
    return out


class StaticBucketLadder(Rule):
    rule_id = "AIL012"
    name = "static-bucket-ladder"
    description = ("literal bucket/tile ladder tuples under runtime/ must "
                   "live in the deriver module (runtime/ladder.py) — the "
                   "static ladder must not silently come back")

    def check_module(self, ctx):
        path = ctx.path.replace("\\", "/")
        if SCOPE_PART not in path or path.endswith(EXEMPT_SUFFIX):
            return []
        rule = self

        class _Visitor(ast.NodeVisitor):
            def __init__(self):
                self.findings = []
                self._stack: list[ast.AST] = []

            def _enter(self, node):
                self._stack.append(node)
                self.generic_visit(node)
                self._stack.pop()

            visit_ClassDef = _enter
            visit_FunctionDef = _enter
            visit_AsyncFunctionDef = _enter

            def _check(self, node):
                run = _leading_ints(node)
                if (len(run) >= MIN_RUN and run[0] == 1
                        and all(b > a for a, b in zip(run, run[1:]))):
                    self.findings.append(ctx.finding(
                        rule.rule_id, node,
                        f"literal bucket ladder {tuple(run)} in runtime/ "
                        "— ladders are derived from traffic "
                        "(runtime/ladder.py); import a named constant "
                        "from the deriver module instead of hard-coding "
                        "the static guess",
                        symbol=enclosing_symbol(self._stack)))
                self.generic_visit(node)

            visit_Tuple = _check
            visit_List = _check

        visitor = _Visitor()
        visitor.visit(ctx.tree)
        return visitor.findings
