"""AIL010 — metrics/docs drift on the ``ai4e_*`` metric-name surface.

The bug class (the mirror of AIL006's config drift): a metric exists in
code but appears nowhere in ``docs/METRICS.md`` — the operator staring
at a dashboard during an incident cannot find out what it means or what
labels it carries — or the docs describe a metric that no longer exists
(a rename that missed the docs; the alert an operator builds on it will
never fire). The first run of this rule found exactly one of the
latter: ``ai4e_trace_current`` was documented as an open-spans gauge
but had only ever been a ``ContextVar`` name in code.

Two checks, run once over the whole project:

1. every metric name registered in code — a string literal as the first
   argument of a ``.counter("ai4e_…")`` / ``.gauge(…)`` /
   ``.histogram(…)`` call — appears in ``docs/METRICS.md``;
2. every ``ai4e_*`` token in ``docs/METRICS.md`` corresponds to a
   registered name (exact, a documented ``name_*`` family mention, or a
   histogram/counter exposition suffix ``_bucket``/``_sum``/``_count``
   of one).

File-path tokens (``ai4e_tpu/metrics/registry.py``) are excluded by
context; the module name ``ai4e_tpu`` is never a metric.
"""

from __future__ import annotations

import ast
import os
import re

from ..core import Finding, ProjectRule

_TOKEN_RE = re.compile(r"ai4e_[a-z0-9_]*[a-z0-9]")
_REGISTER_METHODS = ("counter", "gauge", "histogram")
_DOC_FILE = os.path.join("docs", "METRICS.md")
# Prometheus exposition suffixes a doc may legitimately spell out.
_EXPO_SUFFIXES = ("_bucket", "_sum", "_count")
_NEVER_METRICS = {"ai4e_tpu"}  # the package name, not a metric


def _registered_names(module) -> list[tuple[str, int]]:
    """(metric_name, lineno) for every registry-registration call with a
    literal name. Attribute-based matching (anything ``.counter(…)``)
    deliberately over-collects: a non-registry object with a ``counter``
    method taking an ``ai4e_``-prefixed string literal is not a thing
    this codebase has, and under-collecting would let real metrics ship
    undocumented."""
    out = []
    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Call) or not node.args:
            continue
        func = node.func
        if not (isinstance(func, ast.Attribute)
                and func.attr in _REGISTER_METHODS):
            continue
        arg = node.args[0]
        if (isinstance(arg, ast.Constant) and isinstance(arg.value, str)
                and arg.value.startswith("ai4e_")):
            out.append((arg.value, node.lineno))
    return out


class MetricsDrift(ProjectRule):
    rule_id = "AIL010"
    name = "metrics-drift"
    description = ("every registered ai4e_* metric must appear in "
                   "docs/METRICS.md, and every documented one must exist "
                   "in code")

    def check_project(self, ctx):
        findings: list[Finding] = []
        known: dict[str, tuple[str, int]] = {}
        for module in ctx.modules:
            for name, line in _registered_names(module):
                known.setdefault(name, (module.path, line))
        doc_tokens = self._doc_tokens(ctx.root)
        doc_path = _DOC_FILE.replace(os.sep, "/")
        if not known and not doc_tokens:
            return findings
        documented = {tok for tok, _loc, _family in doc_tokens}
        families = {tok for tok, _loc, family in doc_tokens if family}

        def _snippet(path: str, line: int) -> str:
            try:
                with open(os.path.join(ctx.root, path),
                          encoding="utf-8") as fh:
                    lines = fh.read().splitlines()
                return (lines[line - 1].strip()
                        if 0 < line <= len(lines) else "")
            except OSError:
                return ""

        # Check 1: code side must be documented.
        for name, (path, line) in sorted(known.items()):
            if name in documented or any(
                    name == fam or name.startswith(fam + "_")
                    for fam in families):
                continue
            findings.append(Finding(
                self.rule_id, path, line, 0,
                f"metric {name} is registered in code but documented "
                f"nowhere in {doc_path} — dashboards and alerts cannot "
                "be built on an unexplained series",
                snippet=_snippet(path, line)))

        # Check 2: doc side must exist in code.
        for tok, (path, line), family in sorted(doc_tokens):
            if tok in known:
                continue
            if family and any(name == tok or name.startswith(tok + "_")
                              for name in known):
                continue  # explicit starred family covering real names
            if any(tok == name + suffix for name in known
                   for suffix in _EXPO_SUFFIXES):
                continue  # exposition-suffix spelling of a real histogram
            findings.append(Finding(
                self.rule_id, path, line, 0,
                f"{doc_path} documents {tok} but no code registers it — "
                "stale doc or a rename that missed the docs",
                snippet=_snippet(path, line)))
        return findings

    def _doc_tokens(self, root: str
                    ) -> list[tuple[str, tuple[str, int], bool]]:
        """(token, (doc path, line), is_family) from docs/METRICS.md.
        ``is_family`` = the token is immediately starred (``ai4e_slo_*``).
        Tokens in file-path context (followed by ``/`` or ``.py``) and
        the package name are skipped."""
        path = os.path.join(root, _DOC_FILE)
        rel = _DOC_FILE.replace(os.sep, "/")
        out = []
        try:
            with open(path, encoding="utf-8") as fh:
                text = fh.read()
        except OSError:
            return out
        for i, line in enumerate(text.splitlines(), 1):
            for m in _TOKEN_RE.finditer(line):
                tok = m.group(0)
                rest = line[m.end():]
                if tok in _NEVER_METRICS:
                    continue
                if rest.startswith("/") or rest.startswith(".py"):
                    continue  # file path, not a metric
                family = rest.startswith("*") or rest.startswith("_*")
                out.append((tok, (rel, i), family))
        return out
